"""Matrix / shape-manipulation / indexing / ordering / init ops.

Covers the reference's src/operator/tensor/{matrix_op,indexing_op,init_op,
ordering_op,control_flow_op}.* plus the legacy Concat/SliceChannel/SwapAxis/Pad
layers. ``dot`` maps straight to jnp.dot/einsum — i.e. the MXU — and is the
single most performance-critical lowering in the framework.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import AttrSpec, register

_B2 = ("lhs", "rhs")


@register(
    "dot",
    attrs={
        "transpose_a": AttrSpec("bool", default=False),
        "transpose_b": AttrSpec("bool", default=False),
    },
    input_names=_B2,
)
def _dot(attrs, lhs, rhs):
    """Matrix/tensor product (reference: matrix_op.cc dot). 2D×2D → MXU matmul;
    higher-rank follows the reference's "last axis of lhs, first of rhs" rule."""
    if attrs["transpose_a"]:
        lhs = jnp.moveaxis(lhs, 0, -1) if lhs.ndim > 2 else lhs.T
    if attrs["transpose_b"]:
        rhs = jnp.moveaxis(rhs, -1, 0) if rhs.ndim > 2 else rhs.T
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register(
    "batch_dot",
    attrs={
        "transpose_a": AttrSpec("bool", default=False),
        "transpose_b": AttrSpec("bool", default=False),
    },
    input_names=_B2,
)
def _batch_dot(attrs, lhs, rhs):
    """Batched matmul (reference: matrix_op.cc batch_dot)."""
    if attrs["transpose_a"]:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if attrs["transpose_b"]:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("transpose", attrs={"axes": AttrSpec("shape", default=())})
def _transpose(attrs, data):
    axes = attrs["axes"] or None
    return jnp.transpose(data, axes)


def _reshape_target(shape_spec, in_shape):
    """MXNet Reshape shape-code semantics: 0 copy, -1 infer, -2 copy rest,
    -3 merge two, -4 split (reference: matrix_op-inl.h ReshapeParam)."""
    out = []
    i = 0  # index into in_shape
    j = 0
    spec = list(shape_spec)
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(in_shape[i])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        elif s == -2:
            out.extend(in_shape[i:])
            i = len(in_shape)
        elif s == -3:
            out.append(in_shape[i] * in_shape[i + 1])
            i += 2
        elif s == -4:
            a, b = spec[j + 1], spec[j + 2]
            if a == -1:
                a = in_shape[i] // b
            if b == -1:
                b = in_shape[i] // a
            out.extend([a, b])
            i += 1
            j += 2
        else:
            out.append(s)
            i += 1
        j += 1
    if out.count(-1) > 1:
        raise MXNetError("Reshape: at most one -1 allowed")
    return tuple(out)


@register(
    "Reshape",
    attrs={
        "shape": AttrSpec("shape", default=()),
        "target_shape": AttrSpec("shape", default=()),
        "keep_highest": AttrSpec("bool", default=False),
        "reverse": AttrSpec("bool", default=False),
    },
    aliases=("reshape",),
)
def _reshape(attrs, data):
    spec = attrs["shape"] or attrs["target_shape"]
    if attrs.get("reverse"):
        tgt = _reshape_target(tuple(reversed(spec)), tuple(reversed(data.shape)))
        tgt = tuple(reversed(tgt))
    else:
        tgt = _reshape_target(spec, data.shape)
    return jnp.reshape(data, tgt)


@register("Flatten", aliases=("flatten",))
def _flatten(attrs, data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("expand_dims", attrs={"axis": AttrSpec("int", required=True)})
def _expand_dims(attrs, data):
    return jnp.expand_dims(data, attrs["axis"])


@register(
    "slice",
    attrs={
        "begin": AttrSpec("shape", required=True),
        "end": AttrSpec("shape", required=True),
    },
    aliases=("crop",),
)
def _slice(attrs, data):
    idx = tuple(slice(b, e) for b, e in zip(attrs["begin"], attrs["end"]))
    return data[idx]


@register(
    "slice_axis",
    attrs={
        "axis": AttrSpec("int", required=True),
        "begin": AttrSpec("int", default=0),
        "end": AttrSpec("any", default=None),
    },
)
def _slice_axis(attrs, data):
    ax = attrs["axis"] % data.ndim
    end = attrs["end"]
    end = None if end in (None, "None") else int(end)
    idx = [slice(None)] * data.ndim
    idx[ax] = slice(attrs["begin"], end)
    return data[tuple(idx)]


@register(
    "repeat",
    attrs={"repeats": AttrSpec("int", required=True), "axis": AttrSpec("any", default=None)},
)
def _repeat(attrs, data):
    ax = attrs["axis"]
    ax = None if ax in (None, "None") else int(ax)
    return jnp.repeat(data, attrs["repeats"], axis=ax)


@register("tile", attrs={"reps": AttrSpec("shape", required=True)})
def _tile(attrs, data):
    return jnp.tile(data, attrs["reps"])


@register("reverse", attrs={"axis": AttrSpec("shape", required=True)}, aliases=("flip",))
def _reverse(attrs, data):
    return jnp.flip(data, axis=attrs["axis"])


@register(
    "SwapAxis",
    attrs={"dim1": AttrSpec("int", default=0), "dim2": AttrSpec("int", default=0)},
    aliases=("swapaxes",),
)
def _swapaxis(attrs, data):
    return jnp.swapaxes(data, attrs["dim1"], attrs["dim2"])


def _n_args_names(attrs):
    n = int(attrs.get("num_args", 1))
    return ["arg%d" % i for i in range(n)]


@register(
    "Concat",
    attrs={"num_args": AttrSpec("int", required=True), "dim": AttrSpec("int", default=1)},
    input_names=_n_args_names,
    aliases=("concat",),
)
def _concat(attrs, *args):
    """Concatenate along dim (reference: src/operator/concat.cc)."""
    return jnp.concatenate(args, axis=attrs["dim"])


@register(
    "SliceChannel",
    attrs={
        "num_outputs": AttrSpec("int", required=True),
        "axis": AttrSpec("int", default=1),
        "squeeze_axis": AttrSpec("bool", default=False),
    },
    num_outputs=lambda attrs: int(attrs["num_outputs"]),
    aliases=("split",),
)
def _slice_channel(attrs, data):
    """Split into equal parts along axis (reference: src/operator/slice_channel.cc)."""
    parts = jnp.split(data, attrs["num_outputs"], axis=attrs["axis"])
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=attrs["axis"]) for p in parts]
    return tuple(parts)


@register(
    "Embedding",
    attrs={
        "input_dim": AttrSpec("int", required=True),
        "output_dim": AttrSpec("int", required=True),
        "dtype": AttrSpec("dtype", default=np.float32),
        # reference: Embedding(..., sparse_grad=True) marks the weight for a
        # row-sparse gradient (docs/SPARSE.md). The forward is identical;
        # the flag is metadata the sparse KVStore glue and the GL4xx
        # sharding lint read (sparse.sparse_param_names).
        "sparse_grad": AttrSpec("bool", default=False),
    },
    input_names=("data", "weight"),
)
def _embedding(attrs, data, weight):
    """Lookup-table embedding (reference: indexing_op.cc Embedding). XLA lowers
    this gather to a one-hot matmul on the MXU for small vocabularies."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register(
    "SparseEmbedding",
    attrs={
        "input_dim": AttrSpec("int", required=True),
        "output_dim": AttrSpec("int", required=True),
        "dtype": AttrSpec("dtype", default=np.float32),
    },
    input_names=("data", "weight"),
    aliases=("row_sparse_embedding",),
)
def _sparse_embedding(attrs, data, weight):
    """Embedding whose weight gradient is row-sparse by contract
    (reference: contrib.SparseEmbedding over kRowSparseStorage): the
    backward is a segment-sum over the batch's unique ids
    (``sparse.embedding_backward``) — the (vocab, dim) dense gradient is
    never materialized, and only touched rows reach the optimizer/wire.
    Forward is the same gather; the distinct op name carries the
    ``row_sparse_embedding`` shard-rule category (ops/infer_meta.py) so the
    sharding lint and autoplan price its vocab-sharded placement."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register(
    "take",
    attrs={
        "axis": AttrSpec("int", default=0),
        "mode": AttrSpec("str", default="clip"),
    },
    input_names=("a", "indices"),
)
def _take(attrs, a, indices):
    mode = attrs["mode"]
    idx = indices.astype(jnp.int32)
    return jnp.take(a, idx, axis=attrs["axis"], mode="wrap" if mode == "wrap" else "clip")


@register("batch_take", input_names=("a", "indices"))
def _batch_take(attrs, a, indices):
    """out[i] = a[i, indices[i]] (reference: indexing_op.cc batch_take)."""
    return jnp.take_along_axis(a, indices.astype(jnp.int32)[:, None], axis=1)[:, 0]


@register(
    "one_hot",
    attrs={
        "depth": AttrSpec("int", required=True),
        "on_value": AttrSpec("float", default=1.0),
        "off_value": AttrSpec("float", default=0.0),
        "dtype": AttrSpec("dtype", default=np.float32),
    },
    input_names=("indices",),
)
def _one_hot(attrs, indices):
    hot = jax.nn.one_hot(indices.astype(jnp.int32), attrs["depth"], dtype=attrs["dtype"])
    return hot * (attrs["on_value"] - attrs["off_value"]) + attrs["off_value"]


@register("where", input_names=("condition", "x", "y"))
def _where(attrs, condition, x, y):
    """Elementwise/row select (reference: control_flow_op.cc where)."""
    if condition.ndim == 1 and x.ndim > 1:
        condition = condition.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(condition != 0, x, y)


@register("pick", attrs={"axis": AttrSpec("int", default=1), "keepdims": AttrSpec("bool", default=False)}, input_names=("data", "index"))
def _pick(attrs, data, index):
    ax = attrs["axis"] % data.ndim
    idx = jnp.expand_dims(index.astype(jnp.int32), ax)
    out = jnp.take_along_axis(data, idx, axis=ax)
    if not attrs["keepdims"]:
        out = jnp.squeeze(out, axis=ax)
    return out


# --- ordering (reference: tensor/ordering_op*.cc; cub/thrust → XLA sort) ------
_TOPK_ATTRS = lambda: {
    "axis": AttrSpec("any", default=-1),
    "k": AttrSpec("int", default=1),
    "ret_typ": AttrSpec("str", default="indices"),
    "is_ascend": AttrSpec("bool", default=False),
}


@register("topk", attrs=_TOPK_ATTRS(), num_outputs=lambda a: 2 if a.get("ret_typ") == "both" else 1)
def _topk(attrs, data):
    ax = attrs["axis"]
    ax = data.ndim - 1 if ax in (None, "None") else int(ax) % data.ndim
    k = attrs["k"]
    vals = data if not attrs["is_ascend"] else -data
    moved = jnp.moveaxis(vals, ax, -1)
    top_vals, raw_idx = jax.lax.top_k(moved, k)
    if attrs["is_ascend"]:
        top_vals = -top_vals
    top_vals = jnp.moveaxis(top_vals, -1, ax)
    top_idx = jnp.moveaxis(raw_idx, -1, ax).astype(jnp.float32)
    rt = attrs["ret_typ"]
    if rt == "value":
        return top_vals
    if rt == "both":
        return top_vals, top_idx
    if rt == "mask":
        # 0/1 mask with ones at top-k positions (reference: ordering_op kRetMask)
        onehot = jax.nn.one_hot(raw_idx, moved.shape[-1], dtype=data.dtype)
        mask = jnp.clip(jnp.sum(onehot, axis=-2), 0, 1)
        return jnp.moveaxis(mask, -1, ax)
    if rt != "indices":
        raise MXNetError("topk: unsupported ret_typ %r" % rt)
    return top_idx


@register("sort", attrs={"axis": AttrSpec("any", default=-1), "is_ascend": AttrSpec("bool", default=True)})
def _sort(attrs, data):
    ax = attrs["axis"]
    if ax in (None, "None"):
        data, ax = data.reshape(-1), 0
    out = jnp.sort(data, axis=int(ax))
    return out if attrs["is_ascend"] else jnp.flip(out, axis=int(ax))


@register("argsort", attrs={"axis": AttrSpec("any", default=-1), "is_ascend": AttrSpec("bool", default=True)})
def _argsort(attrs, data):
    ax = attrs["axis"]
    if ax in (None, "None"):
        data, ax = data.reshape(-1), 0
    out = jnp.argsort(data, axis=int(ax))
    if not attrs["is_ascend"]:
        out = jnp.flip(out, axis=int(ax))
    return out.astype(jnp.float32)


# --- init ops (reference: tensor/init_op.cc) ----------------------------------
@register(
    "_zeros",
    attrs={"shape": AttrSpec("shape", default=()), "dtype": AttrSpec("dtype", default=np.float32)},
    input_names=(),
)
def _zeros(attrs):
    return jnp.zeros(attrs["shape"], dtype=attrs["dtype"])


@register(
    "_ones",
    attrs={"shape": AttrSpec("shape", default=()), "dtype": AttrSpec("dtype", default=np.float32)},
    input_names=(),
)
def _ones(attrs):
    return jnp.ones(attrs["shape"], dtype=attrs["dtype"])


@register(
    "_full",
    attrs={
        "shape": AttrSpec("shape", default=()),
        "dtype": AttrSpec("dtype", default=np.float32),
        "value": AttrSpec("float", default=0.0),
    },
    input_names=(),
)
def _full(attrs):
    return jnp.full(attrs["shape"], attrs["value"], dtype=attrs["dtype"])


@register(
    "_arange",
    attrs={
        "start": AttrSpec("float", default=0.0),
        "stop": AttrSpec("any", default=None),
        "step": AttrSpec("float", default=1.0),
        "repeat": AttrSpec("int", default=1),
        "dtype": AttrSpec("dtype", default=np.float32),
    },
    input_names=(),
)
def _arange(attrs):
    stop = attrs["stop"]
    stop = None if stop in (None, "None") else float(stop)
    out = jnp.arange(attrs["start"], stop, attrs["step"], dtype=attrs["dtype"])
    if attrs["repeat"] > 1:
        out = jnp.repeat(out, attrs["repeat"])
    return out


@register(
    "_graph_const",
    attrs={
        # raw little-endian bytes of the folded value — bytes are hashable,
        # so the node freezes cleanly into symbol._eval_node_shape's cache
        # key (an ndarray attr would not)
        "data": AttrSpec("any", required=True),
        "shape": AttrSpec("shape", default=()),
        "dtype": AttrSpec("dtype", default=np.float32),
    },
    input_names=(),
)
def _graph_const(attrs):
    """A constant materialized by the graph-rewrite constant-folding pass
    (analysis/rewrite.py): the one-time host-side evaluation of a subgraph
    whose leaves were all init ops. Never written by frontends directly."""
    arr = np.frombuffer(attrs["data"], dtype=attrs["dtype"])
    return jnp.asarray(arr.reshape(attrs["shape"]))


@register("zeros_like")
def _zeros_like(attrs, data):
    return jnp.zeros_like(data)


@register("ones_like")
def _ones_like(attrs, data):
    return jnp.ones_like(data)


@register(
    "Pad",
    attrs={
        "mode": AttrSpec("str", default="constant"),
        "pad_width": AttrSpec("shape", required=True),
        "constant_value": AttrSpec("float", default=0.0),
    },
    aliases=("pad",),
)
def _pad(attrs, data):
    """N-D padding (reference: src/operator/pad.cc)."""
    pw = attrs["pad_width"]
    pads = [(pw[2 * i], pw[2 * i + 1]) for i in range(data.ndim)]
    mode = attrs["mode"]
    if mode == "constant":
        return jnp.pad(data, pads, mode="constant", constant_values=attrs["constant_value"])
    return jnp.pad(data, pads, mode="edge" if mode == "edge" else "reflect")
