"""Fused optimizer-update ops.

Covers the reference's src/operator/optimizer_op.cc:18-85 (sgd_update,
sgd_mom_update, adam_update, rmsprop_update, rmspropalex_update). These run the
whole update as one traced expression so XLA fuses grad-rescale/clip/wd/update
into a single HBM pass per weight — the TPU analogue of the reference's device
-side kvstore updates. All ops are functional: they RETURN the new weight/state;
the NDArray frontend writes results back through ``out=`` targets.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import AttrSpec, register


def _common(extra=None):
    d = {
        "lr": AttrSpec("float", required=True),
        "wd": AttrSpec("float", default=0.0),
        "rescale_grad": AttrSpec("float", default=1.0),
        "clip_gradient": AttrSpec("float", default=-1.0),
    }
    d.update(extra or {})
    return d


def _prep_grad(grad, attrs):
    g = grad * attrs["rescale_grad"]
    c = attrs["clip_gradient"]
    if c is not None and c > 0:
        g = jnp.clip(g, -c, c)
    return g


@register("sgd_update", attrs=_common(), input_names=("weight", "grad"))
def _sgd_update(attrs, weight, grad):
    g = _prep_grad(grad, attrs)
    return weight - attrs["lr"] * (g + attrs["wd"] * weight)


@register(
    "sgd_mom_update",
    attrs=_common({"momentum": AttrSpec("float", default=0.0)}),
    input_names=("weight", "grad", "mom"),
    num_outputs=2,
    output_names=("weight", "mom"),
)
def _sgd_mom_update(attrs, weight, grad, mom):
    g = _prep_grad(grad, attrs)
    new_mom = attrs["momentum"] * mom - attrs["lr"] * (g + attrs["wd"] * weight)
    return weight + new_mom, new_mom


@register(
    "adam_update",
    attrs=_common(
        {
            "beta1": AttrSpec("float", default=0.9),
            "beta2": AttrSpec("float", default=0.999),
            "epsilon": AttrSpec("float", default=1e-8),
        }
    ),
    input_names=("weight", "grad", "mean", "var"),
    num_outputs=3,
    output_names=("weight", "mean", "var"),
)
def _adam_update(attrs, weight, grad, mean, var):
    g = _prep_grad(grad, attrs) + attrs["wd"] * weight
    b1, b2 = attrs["beta1"], attrs["beta2"]
    new_mean = b1 * mean + (1 - b1) * g
    new_var = b2 * var + (1 - b2) * jnp.square(g)
    w = weight - attrs["lr"] * new_mean / (jnp.sqrt(new_var) + attrs["epsilon"])
    return w, new_mean, new_var


@register(
    "rmsprop_update",
    attrs=_common(
        {
            "gamma1": AttrSpec("float", default=0.95),
            "epsilon": AttrSpec("float", default=1e-8),
            "clip_weights": AttrSpec("float", default=-1.0),
        }
    ),
    input_names=("weight", "grad", "n"),
    num_outputs=2,
    output_names=("weight", "n"),
)
def _rmsprop_update(attrs, weight, grad, n):
    g = _prep_grad(grad, attrs) + attrs["wd"] * weight
    g1 = attrs["gamma1"]
    new_n = g1 * n + (1 - g1) * jnp.square(g)
    w = weight - attrs["lr"] * g / jnp.sqrt(new_n + attrs["epsilon"])
    cw = attrs["clip_weights"]
    if cw is not None and cw > 0:
        w = jnp.clip(w, -cw, cw)
    return w, new_n


@register(
    "rmspropalex_update",
    attrs=_common(
        {
            "gamma1": AttrSpec("float", default=0.95),
            "gamma2": AttrSpec("float", default=0.9),
            "epsilon": AttrSpec("float", default=1e-8),
            "clip_weights": AttrSpec("float", default=-1.0),
        }
    ),
    input_names=("weight", "grad", "n", "g", "delta"),
    num_outputs=4,
    output_names=("weight", "n", "g", "delta"),
)
def _rmspropalex_update(attrs, weight, grad, n, g_state, delta):
    g = _prep_grad(grad, attrs) + attrs["wd"] * weight
    g1, g2 = attrs["gamma1"], attrs["gamma2"]
    new_n = g1 * n + (1 - g1) * jnp.square(g)
    new_g = g1 * g_state + (1 - g1) * g
    new_delta = g2 * delta - attrs["lr"] * g / jnp.sqrt(new_n - jnp.square(new_g) + attrs["epsilon"])
    w = weight + new_delta
    cw = attrs["clip_weights"]
    if cw is not None and cw > 0:
        w = jnp.clip(w, -cw, cw)
    return w, new_n, new_g, new_delta
