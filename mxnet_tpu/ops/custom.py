"""Custom operator: user Python (numpy) code inside the graph.

Counterpart of the reference's Custom op (src/operator/custom/custom.cc +
python/mxnet/operator.py:396 CustomOp/CustomOpProp/register). The reference
calls back into Python through C callbacks from the engine thread; here the
host code is embedded into the traced XLA program with ``jax.pure_callback``
— so a Custom node composes with jit/vjp like any other op — and its backward
is wired through ``jax.custom_vjp`` calling the user's ``backward``.
"""
from __future__ import annotations


import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import AttrSpec, register

_CUSTOM_PROPS = {}


def register_custom(op_type):
    """Decorator registering a CustomOpProp subclass under ``op_type``
    (reference: operator.py register)."""

    def wrap(klass):
        if op_type in _CUSTOM_PROPS:
            raise MXNetError("custom op %r already registered" % op_type)
        _CUSTOM_PROPS[op_type] = klass
        return klass

    return wrap


def _instantiate(attrs):
    op_type = attrs.get("op_type")
    if op_type not in _CUSTOM_PROPS:
        raise MXNetError("unknown custom op_type %r" % op_type)
    kwargs = {k: v for k, v in attrs.items()
              if k != "op_type" and not (k.startswith("__") and k.endswith("__"))
              and v is not None}
    return _CUSTOM_PROPS[op_type](**kwargs)


def _custom_input_names(attrs):
    prop = _instantiate(attrs)
    return list(prop.list_arguments())


def _custom_aux_names(attrs):
    prop = _instantiate(attrs)
    return list(prop.list_auxiliary_states())


def _custom_num_outputs(attrs):
    return len(_instantiate(attrs).list_outputs())


@register(
    "Custom",
    attrs={"op_type": AttrSpec("str", required=True)},
    input_names=_custom_input_names,
    aux_names=_custom_aux_names,
    num_outputs=_custom_num_outputs,
    needs_train_flag=True,
)
def _custom(attrs, inputs, aux, is_train=False):
    prop = _instantiate(attrs)
    data, aux = list(inputs), list(aux or [])
    in_shapes = [list(x.shape) for x in data]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    in_types = [x.dtype for x in data]
    try:
        _, out_types, _ = prop.infer_type(in_types)
    except Exception:
        out_types = [in_types[0] if in_types else np.float32] * len(out_shapes)
    out_struct = tuple(jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
                       for s, t in zip(out_shapes, out_types))
    op = prop.create_operator(None, in_shapes, in_types)
    need_top_grad = getattr(prop, "need_top_grad_", True)

    from ..ndarray import array as nd_array

    def host_forward(*arrays):
        in_nd = [nd_array(np.asarray(a)) for a in arrays[: len(data)]]
        aux_nd = [nd_array(np.asarray(a)) for a in arrays[len(data):]]
        out_nd = [nd_array(np.zeros(tuple(s), np.dtype(t)))
                  for s, t in zip(out_shapes, out_types)]
        op.forward(is_train=is_train, req=["write"] * len(out_nd),
                   in_data=in_nd, out_data=out_nd, aux=aux_nd)
        outs = tuple(o.asnumpy() for o in out_nd)
        return outs if len(outs) > 1 else outs[0]

    def host_backward(*arrays):
        k = len(out_struct)
        ograds = [nd_array(np.asarray(a)) for a in arrays[:k]]
        in_nd = [nd_array(np.asarray(a)) for a in arrays[k : k + len(data)]]
        outs_nd = [nd_array(np.asarray(a)) for a in arrays[k + len(data) : k + len(data) + k]]
        aux_nd = [nd_array(np.asarray(a)) for a in arrays[k + len(data) + k :]]
        in_grad = [nd_array(np.zeros_like(np.asarray(x.asnumpy()))) for x in in_nd]
        op.backward(req=["write"] * len(in_grad), out_grad=ograds,
                    in_data=in_nd, out_data=outs_nd, in_grad=in_grad, aux=aux_nd)
        grads = tuple(g.asnumpy() for g in in_grad)
        return grads if len(grads) > 1 else grads[0]

    in_grad_struct = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in data)

    @jax.custom_vjp
    def run(data_t, aux_t):
        res = jax.pure_callback(host_forward, out_struct if len(out_struct) > 1 else out_struct[0],
                                *data_t, *aux_t, vmap_method="sequential")
        return res if isinstance(res, tuple) else (res,)

    def run_fwd(data_t, aux_t):
        outs = run(data_t, aux_t)
        return outs, (data_t, aux_t, outs)

    def run_bwd(saved, cot):
        data_t, aux_t, outs = saved
        grads = jax.pure_callback(
            host_backward,
            in_grad_struct if len(in_grad_struct) > 1 else in_grad_struct[0],
            *cot, *data_t, *outs, *aux_t, vmap_method="sequential")
        if not isinstance(grads, tuple):
            grads = (grads,)
        return (tuple(grads), tuple(jnp.zeros_like(a) for a in aux_t))

    run.defvjp(run_fwd, run_bwd)
    outs = run(tuple(data), tuple(aux))
    # aux states pass through unchanged (host-side aux mutation would need a
    # write-back channel; custom aux is likewise rare in the reference)
    return tuple(outs), list(aux)
