"""Parameter-shape inference rules.

The reference's ``InferShape`` pass runs bidirectionally so ``simple_bind``
can deduce every weight shape from just the data shape
(/root/reference/src/executor/graph_executor.cc:423, per-op InferShape
functions e.g. fully_connected-inl.h). In the TPU-native design, forward
shape inference comes free from ``jax.eval_shape`` over the op function; the
only genuinely backward-flowing facts are *parameter* shapes (weights, biases,
norm stats, labels), captured here as per-op rules.

Each rule receives the parsed attrs and the list of currently-known input
shapes (``None`` = unknown), ordered ``input_names + aux_names``, and returns
the list with any deducible entries filled in.
"""
from __future__ import annotations


from .rnn import rnn_param_size

RULES = {}


def rule(name):
    def _r(fn):
        RULES[name] = fn
        return fn

    return _r


def _prod(xs):
    p = 1
    for x in xs:
        p *= int(x)
    return p


@rule("FullyConnected")
def _fc(attrs, shapes):
    data = shapes[0]
    if data is not None:
        nh = attrs["num_hidden"]
        d = _prod(data[1:]) if attrs.get("flatten", True) else data[-1]
        if shapes[1] is None:
            shapes[1] = (nh, d)
        if len(shapes) > 2 and shapes[2] is None:
            shapes[2] = (nh,)
    return shapes


@rule("Convolution")
def _conv(attrs, shapes):
    data = shapes[0]
    if data is not None:
        nf, g = attrs["num_filter"], attrs.get("num_group", 1)
        if shapes[1] is None:
            shapes[1] = (nf, data[1] // g) + tuple(attrs["kernel"])
        if len(shapes) > 2 and shapes[2] is None:
            shapes[2] = (nf,)
    return shapes


@rule("Deconvolution")
def _deconv(attrs, shapes):
    data = shapes[0]
    if data is not None:
        nf, g = attrs["num_filter"], attrs.get("num_group", 1)
        if shapes[1] is None:
            shapes[1] = (data[1], nf // g) + tuple(attrs["kernel"])
        if len(shapes) > 2 and shapes[2] is None:
            shapes[2] = (nf,)
    return shapes


@rule("BatchNorm")
def _bn(attrs, shapes):
    data = shapes[0]
    if data is not None:
        c = (data[1],)
        for i in range(1, 5):  # gamma, beta, moving_mean, moving_var
            if shapes[i] is None:
                shapes[i] = c
    return shapes


@rule("InstanceNorm")
def _in(attrs, shapes):
    data = shapes[0]
    if data is not None:
        for i in (1, 2):
            if shapes[i] is None:
                shapes[i] = (data[1],)
    return shapes


@rule("LeakyReLU")
def _lrelu(attrs, shapes):
    data = shapes[0]
    if data is not None and len(shapes) > 1 and shapes[1] is None:
        shapes[1] = (data[1],)
    return shapes


@rule("Embedding")
@rule("SparseEmbedding")
def _embedding(attrs, shapes):
    if shapes[1] is None:
        shapes[1] = (attrs["input_dim"], attrs["output_dim"])
    return shapes


@rule("RNN")
def _rnn_shapes(attrs, shapes):
    data = shapes[0]
    if data is not None:
        T, N, I = data
        H, L = attrs["state_size"], attrs["num_layers"]
        d = 2 if attrs.get("bidirectional") else 1
        if shapes[1] is None:
            shapes[1] = (rnn_param_size(L, I, H, attrs.get("bidirectional", False), attrs["mode"]),)
        if shapes[2] is None:
            shapes[2] = (L * d, N, H)
        if len(shapes) > 3 and shapes[3] is None:
            shapes[3] = (L * d, N, H)
    return shapes


@rule("SoftmaxOutput")
def _softmax_out(attrs, shapes):
    data = shapes[0]
    if data is not None and shapes[1] is None:
        if attrs.get("multi_output") and len(data) > 2:
            shapes[1] = (data[0],) + tuple(data[2:])
        elif attrs.get("preserve_shape"):
            shapes[1] = tuple(data[:-1])
        else:
            shapes[1] = (data[0],)
    return shapes


def _label_like_data(attrs, shapes):
    if shapes[0] is not None and shapes[1] is None:
        shapes[1] = tuple(shapes[0])
    return shapes


for _n in ("LinearRegressionOutput", "LogisticRegressionOutput", "MAERegressionOutput"):
    RULES[_n] = _label_like_data


@rule("SVMOutput")
def _svm_out(attrs, shapes):
    data = shapes[0]
    if data is not None and shapes[1] is None:
        shapes[1] = (data[0],)
    return shapes


@rule("IdentityAttachKLSparseReg")
def _klreg(attrs, shapes):
    return shapes
