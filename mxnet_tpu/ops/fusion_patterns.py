"""Declarative pattern registry for the subgraph fusion engine.

The generalization of the conv+BN special case (fusion.py): each pattern is
a matcher over the Symbol DAG plus one-or-more fused lowerings, gated per
(shape, dtype) by the persistent measure-and-cache autotuner
(``fusion_tune.py``) instead of a committed WINS table. The patterns here
cover exactly the chains "Operator Fusion in XLA" (PAPERS.md) names as the
ones XLA leaves on the table over our Symbol DAG:

- ``matmul_bias_act``   — FullyConnected(+bias) → Activation, onto the
  Pallas epilogue kernel (``ops/pallas_matmul_bias_act.py``).
- ``attention``         — the fused MultiHeadAttention op, onto block-causal
  XLA (skips the masked upper-triangle key blocks: ~2× fewer score FLOPs on
  causal sites, exact parity) or the Pallas flash kernel on TPU.
- ``norm_residual``     — the LayerNorm composition the transformer zoo
  emits (mean/center/var/rsqrt/affine over broadcast ops), as one traced
  function.
- ``elemwise_chain``    — runs of single-consumer unary elementwise ops,
  composed into one lowering unit.

Contract per pattern:

- ``match(node, ctx)``       — try to root a match at ``node``; returns a
  ``Match`` (root, interior nodes, meta) or None. Interior nodes must be
  single-output, aux-free, rng-free, unclaimed, and not program outputs —
  the executor elides them behind lazy markers.
- ``externals(meta, ins, resolve)`` — recover the subgraph's EXTERNAL
  input values from the root's (possibly lazy) ``ins`` at trace time.
- ``build(meta, args)``      — ``(baseline_fn, [(name, fused_fn), ...])``:
  the unfused composition (the measurement reference AND the semantic
  spec) and the candidate fused lowerings for these concrete shapes. An
  empty candidate list means "nothing to measure here" and the site runs
  unfused.
- ``reject_reason(node, ctx)`` — for the GL303 explainer: why a
  near-miss node did not root a match (or None when it did / is not this
  pattern's root op).

The matchers deliberately refuse anything stateful: no aux (BN moving
stats), no rng (Dropout), no multi-output interiors — the fallback path
must be bit-identical to the unfused graph.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import get_op

__all__ = ["Match", "Pattern", "get_patterns", "pattern_names", "sig_of",
           "tuner_build"]


class Match:
    __slots__ = ("root", "interior", "meta")

    def __init__(self, root, interior, meta):
        self.root, self.interior, self.meta = root, list(interior), dict(meta)


class Pattern:
    name = None
    inference = True  # may engage on grad-less (is_train=False) executions

    def key_variant(self, meta):
        """The meta component of the tune-cache key (shape-independent)."""
        return ""

    def match(self, node, ctx):
        raise NotImplementedError

    def externals(self, meta, ins, resolve):
        raise NotImplementedError

    def build(self, meta, args):
        raise NotImplementedError

    def reject_reason(self, node, ctx):
        return None


def sig_of(args):
    """Canonical shape/dtype signature of the external inputs — the tune
    cache key's site component."""
    return ";".join("%s%s" % (str(np.dtype(a.dtype).name),
                              tuple(a.shape)) for a in args)


# ------------------------------------------------------------ schedule helpers
def _sched_budget():
    """How many schedule variants (beyond the planner default) a pattern
    may emit per candidate family (``MXNET_FUSION_TUNE_SCHEDULES``)."""
    from .. import fusion_tune

    return fusion_tune.schedule_budget()


def _sname(base, **kv):
    from .. import fusion_tune

    return fusion_tune.sched_name(base, **kv)


import contextlib
import threading

_tuner_scope = threading.local()


@contextlib.contextmanager
def tuner_build():
    """Marks a ``build()`` call made to CONSTRUCT MEASUREMENT candidates
    (the auto-mode tuner): force-gated interpret candidates are excluded
    inside this scope, so an inference-map force (e.g. a serving pin of
    ``attention=pallas_flash``) can never leak emulated off-TPU Pallas
    into a training-side measurement."""
    _tuner_scope.active = True
    try:
        yield
    finally:
        _tuner_scope.active = False


def _forced_lowering_requested(pattern_name, prefix):
    """Whether MXNET_FUSED_PATTERNS[_INFER] forces a lowering whose name
    starts with ``prefix`` for this pattern — the opt-in that makes
    ``build`` include interpret-mode Pallas candidates off-TPU (auto-mode
    tuning never measures interpret kernels at real shapes: the emulation
    is orders of magnitude off the question being asked, which is also
    why the ``tuner_build`` scope suppresses this check entirely)."""
    if getattr(_tuner_scope, "active", False):
        return False
    from .. import fusion

    for infer in (False, True):
        m = fusion.enabled_patterns(infer=infer).get(pattern_name, "0")
        if m not in ("0", "1", "auto") and m.startswith(prefix):
            return True
    return False


# --------------------------------------------------------------- match helpers
def _sole_consumer(ctx, node):
    """The single consumer of ``node``'s output 0, or None."""
    cons = ctx.consumers.get(id(node), [])
    if len(cons) == 1 and cons[0][1] == 0:
        return cons[0][0]
    return None


def _interior_ok(ctx, node):
    """Whether ``node`` may be elided behind a lazy marker."""
    if node.is_variable or id(node) in ctx.claimed:
        return False
    if id(node) in ctx.output_ids:
        return False  # its value is a program output: must materialize
    op = get_op(node.op)
    return (node.num_outputs() == 1 and not op.needs_rng
            and not op.needs_train_flag
            and not op.aux_names(node.parsed_attrs()))


def _apply1(node, *ins):
    """Run a single-output, stateless node on concrete values — the exact
    unfused semantics (same opdef the interpreter would call)."""
    outs, _ = get_op(node.op).apply(node.parsed_attrs(), list(ins),
                                    aux=[], is_train=False, rng=None)
    return outs[0]


# ------------------------------------------------------------ matmul_bias_act
class MatmulBiasAct(Pattern):
    """FullyConnected(+bias) → Activation(relu|sigmoid|tanh|softrelu)."""

    name = "matmul_bias_act"

    def key_variant(self, meta):
        return "%s%s%s" % (meta["act"],
                           "" if meta["flatten"] else ",noflat",
                           ",nobias" if meta["no_bias"] else "")

    _ACTS = ("relu", "sigmoid", "tanh", "softrelu")

    def match(self, node, ctx):
        if node.op != "Activation" or id(node) in ctx.claimed:
            return None
        act = node.parsed_attrs().get("act_type")
        if act not in self._ACTS:
            return None
        if not node.inputs or node.inputs[0][1] != 0:
            return None
        fc = node.inputs[0][0]
        if fc.is_variable or fc.op != "FullyConnected":
            return None
        if not _interior_ok(ctx, fc) or _sole_consumer(ctx, fc) is not node:
            return None
        a = fc.parsed_attrs()
        return Match(node, [fc], {"act": act,
                                  "flatten": bool(a.get("flatten", True)),
                                  "no_bias": bool(a.get("no_bias", False))})

    def reject_reason(self, node, ctx):
        # a NEAR miss only: some consumer IS a fusable Activation, yet the
        # match failed. A FullyConnected that simply isn't followed by an
        # activation (every classifier head) is not this pattern's business.
        if node.op != "FullyConnected":
            return None
        cons = ctx.consumers.get(id(node), [])
        acts = [c for c, oi in cons if oi == 0 and c.op == "Activation"
                and c.parsed_attrs().get("act_type") in self._ACTS]
        if not acts:
            return None
        if len(cons) != 1:
            return ("its output has %d consumers; the activation epilogue "
                    "needs the FullyConnected consumed exactly once"
                    % len(cons))
        if id(node) in ctx.output_ids:
            return "its output is a program output and must materialize"
        return None

    def externals(self, meta, ins, resolve):
        lazy = ins[0]
        fc_ins = [resolve(v) for v in lazy.ins]
        return tuple(fc_ins)  # (x, w) or (x, w, b)

    def build(self, meta, args):
        act = meta["act"]
        flatten = meta["flatten"]
        act_fn = {"relu": lambda y: jnp.maximum(y, 0),
                  "sigmoid": jax.nn.sigmoid,
                  "tanh": jnp.tanh,
                  "softrelu": lambda y: jnp.logaddexp(y, 0.0)}[act]

        def baseline(x, w, b=None):
            if flatten:
                x2 = x.reshape((x.shape[0], -1)) if x.ndim != 2 else x
                y = jnp.dot(x2, w.T)
            else:
                y = jnp.einsum("...i,oi->...o", x, w)
            if b is not None:
                y = y + b
            return act_fn(y)

        from . import pallas_matmul_bias_act as pk

        x, w = args[0], args[1]
        if meta["flatten"]:
            m = int(x.shape[0])
            k = int(np.prod(x.shape[1:]))
        else:
            m = int(np.prod(x.shape[:-1]))
            k = int(x.shape[-1])
        n = int(w.shape[0])
        cands = []
        if k == int(w.shape[1]):
            blocks = pk.block_candidates(
                m, k, n, act, itemsize=jnp.dtype(x.dtype).itemsize)

            def make(bm, bn):
                def fused(x, w, b=None, _m=m, _k=k, _n=n, _bm=bm, _bn=bn):
                    x2 = x.reshape((_m, _k))
                    bb = b if b is not None else jnp.zeros((_n,), x.dtype)
                    y = pk.matmul_bias_act(x2, w, bb, meta["act"], _bm, _bn)
                    if meta["flatten"]:
                        return y
                    return y.reshape(x.shape[:-1] + (_n,))

                return fused

            if blocks:
                # planner default keeps the bare name (v1 cache records
                # resolve to it); the schedule variants carry their blocks
                cands.append(("pallas", make(*blocks[0])))
                for bm, bn in blocks[1:1 + _sched_budget()]:
                    cands.append((_sname("pallas", bm=bm, bn=bn),
                                  make(bm, bn)))
        return baseline, cands


# ------------------------------------------------------------------ attention
class Attention(Pattern):
    """The fused MultiHeadAttention op. Candidate lowerings per site shape:

    - ``block_causal`` (causal, T == S): never computes the masked
      upper-triangle key blocks — ~half the score FLOPs, exact parity.
    - ``chunked_kv`` (decode/cross-attention: T_q != T_kv and/or no causal
      mask): streaming online-softmax over key chunks, so the (T, S) score
      matrix never materializes whole — the serving-side decode lowering.
    - ``pallas_flash`` (TPU; off-TPU only when force-named — interpret
      mode): the hand-tiled flash kernel, fwd AND bwd (``custom_vjp``
      online-softmax recompute backward), so TRAINING through this site
      stops stashing the (B, H, T, S) probability tensor.

    Each family fans out over the autotuner's bounded schedule space
    (block/chunk sizes), measured against the op's own dense lowering."""

    name = "attention"

    def key_variant(self, meta):
        return ("causal" if meta["causal"] else "full") + (
            ",s%g" % meta["scale"] if meta["scale"] > 0 else "")

    _OPS = ("_contrib_MultiHeadAttention", "MultiHeadAttention")
    _BLOCKS = (128, 64, 32)

    def match(self, node, ctx):
        if node.op not in self._OPS or id(node) in ctx.claimed:
            return None
        a = node.parsed_attrs()
        return Match(node, [], {"causal": bool(a.get("causal")),
                                "scale": float(a.get("scale", -1.0))})

    def reject_reason(self, node, ctx):
        return None  # every attention node roots a match

    def externals(self, meta, ins, resolve):
        return tuple(resolve(v) for v in ins)  # (q, k, v)

    _CHUNKS = (128, 256, 64, 32)

    def build(self, meta, args):
        q, k, _ = args
        causal = meta["causal"]
        scale = meta["scale"] if meta["scale"] > 0 else (
            1.0 / float(np.sqrt(q.shape[-1])))
        T, S = q.shape[2], k.shape[2]

        def baseline(q, k, v):
            # the registered op's dense XLA path, verbatim semantics
            q32, k32, v32 = (t.astype("float32") for t in (q, k, v))
            s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
            if causal:
                Tq, Sk = s.shape[-2], s.shape[-1]
                mask = jnp.tril(jnp.ones((Tq, Sk), bool), k=Sk - Tq)
                s = jnp.where(mask, s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v32).astype(q.dtype)

        def make_block_causal(bq):
            def block_causal(q, k, v, _bq=bq):
                # query block i attends keys [0, (i+1)*bq): the masked
                # upper-triangle key blocks are never computed at all
                q32, k32, v32 = (t.astype("float32") for t in (q, k, v))
                outs = []
                for i in range(T // _bq):
                    qi = q32[:, :, i * _bq:(i + 1) * _bq]
                    end = (i + 1) * _bq
                    s = jnp.einsum("bhqd,bhkd->bhqk", qi,
                                   k32[:, :, :end]) * scale
                    mask = (jnp.arange(end)[None, :]
                            <= (jnp.arange(_bq) + i * _bq)[:, None])
                    s = jnp.where(mask, s, -jnp.inf)
                    p = jax.nn.softmax(s, axis=-1)
                    outs.append(jnp.einsum("bhqk,bhkd->bhqd", p,
                                           v32[:, :, :end]))
                return jnp.concatenate(outs, axis=2).astype(q.dtype)

            return block_causal

        def make_chunked(ck):
            def chunked(q, k, v, _ck=ck):
                # streaming online softmax over key chunks: the (T, S)
                # score matrix exists only one (T, ck) slab at a time.
                # Bottom-right causal alignment (row r sees cols <= r+S-T)
                # matches the op; with S >= T the first chunk's lowest
                # cols are visible to every row, so the running max is
                # real before any fully-masked tail entry (whose
                # exp(-1e30 - m) underflows to exactly 0).
                q32 = q.astype(jnp.float32) * scale
                k32, v32 = k.astype(jnp.float32), v.astype(jnp.float32)
                B, H, Tq, D = q.shape
                Sk = k.shape[2]
                off = Sk - Tq
                rows = jnp.arange(Tq)
                neg = jnp.float32(-1e30)

                def body(carry, i):
                    m, l, acc = carry
                    kc = jax.lax.dynamic_slice_in_dim(k32, i * _ck, _ck,
                                                      axis=2)
                    vc = jax.lax.dynamic_slice_in_dim(v32, i * _ck, _ck,
                                                      axis=2)
                    s = jnp.einsum("bhqd,bhkd->bhqk", q32, kc)
                    if causal:
                        cols = i * _ck + jnp.arange(_ck)
                        s = jnp.where(cols[None, :] <= rows[:, None] + off,
                                      s, neg)
                    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
                    alpha = jnp.exp(m - m_new)
                    p = jnp.exp(s - m_new)
                    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
                    acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd",
                                                       p, vc)
                    return (m_new, l_new, acc_new), None

                init = (jnp.full((B, H, Tq, 1), neg),
                        jnp.zeros((B, H, Tq, 1), jnp.float32),
                        jnp.zeros((B, H, Tq, D), jnp.float32))
                (_, l, acc), _ = jax.lax.scan(body, init,
                                              jnp.arange(Sk // _ck))
                return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

            return chunked

        budget = _sched_budget()
        cands = []
        if causal and T == S:
            bqs = [b for b in self._BLOCKS if T % b == 0 and T > b]
            if bqs:
                cands.append(("block_causal", make_block_causal(bqs[0])))
                cands.extend((_sname("block_causal", bq=b),
                              make_block_causal(b))
                             for b in bqs[1:1 + budget])
        elif not causal or S >= T:
            # decode/cross-attention shapes: T_q != T_kv and/or no mask
            cks = [c for c in self._CHUNKS if S % c == 0 and S > c]
            if cks:
                cands.append(("chunked_kv", make_chunked(cks[0])))
                cands.extend((_sname("chunked_kv", ck=c), make_chunked(c))
                             for c in cks[1:1 + budget])
        on_tpu = jax.default_backend() == "tpu"
        if on_tpu or _forced_lowering_requested(self.name, "pallas_flash"):
            from . import pallas_attention as pa

            interp = not on_tpu

            def make_flash(bq, bk):
                def flash(q, k, v, _bq=bq, _bk=bk):
                    return pa.flash_attention(
                        q, k, v, causal=causal,
                        scale=max(meta["scale"], 0.0),
                        block_q=_bq, block_k=_bk, interpret=interp)

                return flash

            scheds = pa.block_schedules(q.shape, k.shape, causal=causal)
            if scheds:
                cands.append(("pallas_flash", make_flash(*scheds[0])))
                cands.extend((_sname("pallas_flash", q=bq, k=bk),
                              make_flash(bq, bk))
                             for bq, bk in scheds[1:1 + budget])
        return baseline, cands


# -------------------------------------------------------------- norm_residual
def _is_mean_last(node):
    if node.op != "mean":
        return False
    a = node.parsed_attrs()
    return (tuple(a.get("axis") or ()) == (-1,) and a.get("keepdims")
            and not a.get("exclude"))


class NormResidual(Pattern):
    """The LayerNorm composition the transformer zoo emits:

        mean → broadcast_sub → square → mean → +eps → rsqrt
             → broadcast_mul → broadcast_mul(gamma) → broadcast_add(beta)

    rooted at the final broadcast_add (the normalized, affine output the
    residual stream consumes)."""

    name = "norm_residual"

    def key_variant(self, meta):
        return "eps%g" % meta["eps"]

    def _chain(self, node, ctx):
        """The matched interior chain + slots, or (None, reason)."""
        if node.op != "broadcast_add" or len(node.inputs) != 2:
            return None, "not a 2-input broadcast_add"
        mul1 = mul1_slot = None
        for slot, (inp, oi) in enumerate(node.inputs):
            if (oi == 0 and not inp.is_variable and inp.op == "broadcast_mul"
                    and _interior_ok(ctx, inp)
                    and _sole_consumer(ctx, inp) is node):
                mul1, mul1_slot = inp, slot
                break
        if mul1 is None:
            return None, "no sole-consumer broadcast_mul feeds the add"
        mul0 = mul0_slot = None
        for slot, (inp, oi) in enumerate(mul1.inputs):
            if (oi == 0 and not inp.is_variable and inp.op == "broadcast_mul"
                    and _interior_ok(ctx, inp)
                    and _sole_consumer(ctx, inp) is mul1):
                mul0, mul0_slot = inp, slot
                break
        if mul0 is None or len(mul1.inputs) != 2:
            return None, "no gamma-scale broadcast_mul under the affine add"
        if len(mul0.inputs) != 2:
            return None, "normalize mul is not 2-input"
        cent = rs = cent_slot = None
        for slot, (inp, oi) in enumerate(mul0.inputs):
            if oi != 0 or inp.is_variable:
                return None, "normalize mul has a variable operand"
            if inp.op == "broadcast_sub":
                cent, cent_slot = inp, slot
            elif inp.op == "rsqrt":
                rs = inp
        if cent is None or rs is None:
            return None, "normalize mul is not centered*rsqrt"
        if not _interior_ok(ctx, rs) or _sole_consumer(ctx, rs) is not mul0:
            return None, "rsqrt output is consumed outside the chain"
        ps = rs.inputs[0][0] if rs.inputs else None
        if (ps is None or ps.is_variable or ps.op != "_plus_scalar"
                or not _interior_ok(ctx, ps)
                or _sole_consumer(ctx, ps) is not rs):
            return None, "no epsilon _plus_scalar under the rsqrt"
        m2 = ps.inputs[0][0]
        if (m2.is_variable or not _is_mean_last(m2)
                or not _interior_ok(ctx, m2)
                or _sole_consumer(ctx, m2) is not ps):
            return None, "variance is not a keepdims mean over the last axis"
        sq = m2.inputs[0][0]
        if (sq.is_variable or sq.op != "square" or not _interior_ok(ctx, sq)
                or _sole_consumer(ctx, sq) is not m2):
            return None, "variance operand is not square(centered)"
        if sq.inputs[0][0] is not cent:
            return None, "square input is not the centered activation"
        if not _interior_ok(ctx, cent):
            return None, "centered activation cannot be elided"
        cent_cons = {id(c) for c, _ in ctx.consumers.get(id(cent), [])}
        if cent_cons != {id(mul0), id(sq)}:
            return None, ("centered activation is consumed outside the "
                          "chain")
        if len(cent.inputs) != 2 or cent.inputs[0][1] != 0:
            return None, "center sub has unexpected inputs"
        m1 = cent.inputs[1][0]
        if (m1.is_variable or not _is_mean_last(m1)
                or not _interior_ok(ctx, m1)
                or _sole_consumer(ctx, m1) is not cent):
            return None, "center subtrahend is not a keepdims mean"
        if (m1.inputs[0][0] is not cent.inputs[0][0]
                or m1.inputs[0][1] != cent.inputs[0][1]):
            return None, "mean and center read different inputs"
        meta = {"eps": float(ps.parsed_attrs()["scalar"]),
                "mul1_slot": mul1_slot, "mul0_slot": mul0_slot,
                "cent_slot": cent_slot}
        return ([mul1, mul0, cent, rs, ps, m2, sq, m1], meta)

    def match(self, node, ctx):
        if node.op != "broadcast_add" or id(node) in ctx.claimed:
            return None
        interior, meta = self._chain(node, ctx)
        if interior is None:
            return None
        if any(id(n) in ctx.claimed for n in interior):
            return None
        return Match(node, interior, meta)

    def externals(self, meta, ins, resolve):
        l_mul1 = ins[meta["mul1_slot"]]
        beta = resolve(ins[1 - meta["mul1_slot"]])
        l_mul0 = l_mul1.ins[meta["mul0_slot"]]
        gamma = resolve(l_mul1.ins[1 - meta["mul0_slot"]])
        l_cent = l_mul0.ins[meta["cent_slot"]]
        x = resolve(l_cent.ins[0])
        return (x, gamma, beta)

    def build(self, meta, args):
        eps = meta["eps"]

        def baseline(x, gamma, beta):
            mean = jnp.mean(x, axis=-1, keepdims=True)
            cent = x - mean
            var = jnp.mean(jnp.square(cent), axis=-1, keepdims=True)
            inv = jax.lax.rsqrt(var + eps)
            return (cent * inv) * gamma + beta

        def onepass(x, gamma, beta):
            # E[x²]−E[x]² halves the reduction passes over x; numerics
            # differ at ~1e-6 rel (the tuner's parity check is the contract)
            x32 = x.astype(jnp.float32)
            mean = jnp.mean(x32, axis=-1, keepdims=True)
            msq = jnp.mean(x32 * x32, axis=-1, keepdims=True)
            inv = jax.lax.rsqrt(jnp.maximum(msq - mean * mean, 0.0) + eps)
            out = (x32 - mean) * inv
            return (out * gamma + beta).astype(x.dtype)

        # "fused" (the identical recomposition, bit-safe under force) is
        # first so =1 engages it; the tuner measures all and only a real
        # winner clears the margin
        cands = [("fused", baseline), ("onepass", onepass)]

        # the Pallas kernel lowering (ops/pallas_norm_residual.py): one
        # VMEM-resident tile per row block, fwd AND bwd. TPU always;
        # off-TPU only when force-named (interpret mode, parity tests)
        from . import pallas_norm_residual as pn

        x = args[0]
        on_tpu = jax.default_backend() == "tpu"
        if on_tpu or _forced_lowering_requested(self.name, "pallas"):
            itemsize = jnp.dtype(x.dtype).itemsize
            brs = pn.block_candidates(x.shape, itemsize)
            interp = not on_tpu

            def make_pallas(br):
                def fused_pallas(x, gamma, beta, _br=br):
                    # gamma/beta may carry broadcast shapes ((1,1,D)); the
                    # reshape is traced, so its transpose restores the
                    # cotangent shape
                    D = x.shape[-1]
                    return pn.layer_norm_affine(
                        x, gamma.reshape(D), beta.reshape(D), eps,
                        block_rows=_br, interpret=interp)

                return fused_pallas

            if brs:
                cands.append(("pallas", make_pallas(brs[0])))
                cands.extend((_sname("pallas", br=b), make_pallas(b))
                             for b in brs[1:1 + _sched_budget()])
        return baseline, cands


# ------------------------------------------------------------- elemwise_chain
class ElemwiseChain(Pattern):
    """Runs of ≥2 single-consumer unary elementwise ops, composed into one
    lowering unit (one fusion decision instead of N).

    ``tunable = False``: the composed lowering is computation-identical to
    the unfused chain (XLA fuses both the same way), so auto mode never
    measures it — a guaranteed-rejection tune would only add cold-start
    latency. The pattern exists as a grouping/observability unit and as
    the seam future kernel lowerings slot into; ``=1`` force-engages."""

    name = "elemwise_chain"
    tunable = False

    def key_variant(self, meta):
        parts = []
        for n in meta["nodes"]:
            if n.op == "Activation":
                parts.append(n.parsed_attrs().get("act_type"))
            elif n.op.endswith("_scalar"):
                parts.append("%s(%g)" % (n.op, n.parsed_attrs()["scalar"]))
            else:
                parts.append(n.op)
        return "-".join(parts)

    _UNARY = frozenset({
        "abs", "square", "sqrt", "rsqrt", "exp", "log", "log1p", "expm1",
        "negative", "reciprocal", "relu", "sigmoid", "tanh", "softsign",
        "_plus_scalar", "_minus_scalar", "_mul_scalar", "_div_scalar",
    })

    def _link_ok(self, node):
        if node.is_variable:
            return False
        if node.op == "Activation":
            return node.parsed_attrs().get("act_type") in (
                "relu", "sigmoid", "tanh", "softrelu")
        return node.op in self._UNARY

    def match(self, node, ctx):
        if id(node) in ctx.claimed or node.is_variable:
            return None
        if not self._link_ok(node):
            return None
        # only root at the END of a chain: a sole whitelisted consumer
        # would extend it, so let that consumer root instead
        nxt = _sole_consumer(ctx, node)
        if (nxt is not None and self._link_ok(nxt)
                and id(nxt) not in ctx.claimed
                and id(node) not in ctx.output_ids):
            return None
        chain = []
        cur = node
        while True:
            if not cur.inputs or cur.inputs[0][1] != 0:
                break
            prev = cur.inputs[0][0]
            if (not self._link_ok(prev) or not _interior_ok(ctx, prev)
                    or _sole_consumer(ctx, prev) is not cur):
                break
            chain.append(prev)
            cur = prev
        if not chain:
            return None
        nodes = list(reversed(chain)) + [node]  # innermost-first, root last
        return Match(node, chain, {"nodes": nodes})

    def externals(self, meta, ins, resolve):
        from .. import fusion

        v = ins[0]
        while isinstance(v, fusion.Lazy):
            v = v.ins[0]
        return (resolve(v),)

    def build(self, meta, args):
        # chain ops captured at plan time ride in via meta["nodes"]
        nodes = meta["nodes"]  # innermost-first list incl. root last

        def baseline(x):
            for n in nodes:
                x = _apply1(n, x)
            return x

        return baseline, [("fused", baseline)]


_PATTERNS = (Attention(), MatmulBiasAct(), NormResidual(), ElemwiseChain())


def get_patterns():
    """All registered patterns, in matching-priority order."""
    return _PATTERNS


def pattern_names():
    return tuple(p.name for p in _PATTERNS)
