"""Matmul with a bias + activation epilogue, as a Pallas TPU kernel.

The matmul+bias+act chain is the shape "Operator Fusion in XLA" (PAPERS.md)
calls out as the one XLA reassociates poorly around the MXU: the bias add
and activation are a separate elementwise pass that re-reads the matmul
output from HBM. This kernel applies both on the f32 MXU accumulator while
the output tile is still in VMEM — one HBM write for the activated output,
zero extra reads:

    C = act(A @ Wᵀ + b)        A: (M, K)  W: (N, K)  b: (N,)

W rides in the framework's FullyConnected layout (N, K); the kernel
contracts over each operand's axis 1 directly (``dot_general``), so no
transpose materializes. Grid (N/bn, M/bm) with K whole per tile, the
``ops/pallas_matmul_stats.py`` geometry.

Backward is deliberately XLA (``custom_vjp``): dpre is recovered FROM THE
ACTIVATED OUTPUT (relu: mask(y>0); sigmoid: y(1−y); tanh: 1−y²; softrelu:
1−e^{−y}), so no pre-activation stash exists — the three backward matmuls
are plain MXU ops XLA already schedules well. Gating is the pattern
engine's job (``ops/fusion_patterns.py`` + the fusion_tune measured
verdict); this module only refuses shapes that do not tile (``supported``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["matmul_bias_act", "supported", "block_candidates",
           "ACTIVATIONS"]

# activation -> (apply on f32, derivative from the ACTIVATED output)
ACTIVATIONS = {
    "relu": (lambda p: jnp.maximum(p, 0.0),
             lambda y: (y > 0).astype(jnp.float32)),
    "sigmoid": (jax.nn.sigmoid, lambda y: y * (1.0 - y)),
    "tanh": (jnp.tanh, lambda y: 1.0 - y * y),
    # y = log1p(e^p)  =>  act'(p) = sigmoid(p) = 1 - e^{-y}
    "softrelu": (lambda p: jnp.logaddexp(p, 0.0),
                 lambda y: 1.0 - jnp.exp(-y)),
}


def supported(m, k, n, act, block_m=512, block_n=256, itemsize=2):
    """Whether (M, K) @ (N, K)ᵀ tiles within the VMEM budget (the
    pallas_matmul_stats contract: K whole per tile, bm % 8, bn % 128)."""
    if act not in ACTIVATIONS:
        return False
    bm, bn = min(block_m, m), min(block_n, n)
    vmem = (bm * k + k * bn) * itemsize + bm * bn * 4 + bn * 4
    return (m % bm == 0 and n % bn == 0 and bm % 8 == 0 and bn % 128 == 0
            and vmem <= 12 * 1024 * 1024)


def _kernel(a_ref, w_ref, b_ref, y_ref, *, act):
    p = jax.lax.dot_general(a_ref[...], w_ref[...],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    p = p + b_ref[...].astype(jnp.float32)
    y_ref[...] = ACTIVATIONS[act][0](p).astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnames=("act", "block_m", "block_n",
                                             "interpret"))
def _fwd_call(a, w, b, act, block_m, block_n, interpret):
    import jax.experimental.pallas as pl

    M, K = a.shape
    N = w.shape[0]
    bm, bn = min(block_m, M), min(block_n, N)
    assert supported(M, K, N, act, bm, bn, itemsize=a.dtype.itemsize), (
        a.shape, w.shape, a.dtype, act)
    m_tiles, n_tiles = M // bm, N // bn

    from jax.experimental.pallas import tpu as pltpu

    params = None if interpret else pltpu.CompilerParams(
        dimension_semantics=(pltpu.GridDimensionSemantics.PARALLEL,
                             pltpu.GridDimensionSemantics.PARALLEL))
    return pl.pallas_call(
        functools.partial(_kernel, act=act),
        grid=(n_tiles, m_tiles),
        in_specs=[
            pl.BlockSpec((bm, K), lambda n, m: (m, 0)),
            pl.BlockSpec((bn, K), lambda n, m: (n, 0)),
            pl.BlockSpec((1, bn), lambda n, m: (0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda n, m: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), a.dtype),
        compiler_params=params,
        interpret=interpret,
    )(a, w, b.reshape(1, N))


def _interpret_mode():
    return jax.default_backend() != "tpu"


def block_candidates(m, k, n, act, itemsize=2):
    """The bounded (block_m, block_n) schedule space the autotuner measures
    for this shape (docs/PERF.md §15): the planner default first, then the
    supported variants with a DISTINCT effective tiling (a variant that
    clamps to the same (bm, bn) as the default would measure the identical
    program twice)."""
    seen, out = set(), []
    for bm, bn in ((512, 256), (256, 256), (512, 128), (256, 128),
                   (128, 256), (1024, 256), (512, 512)):
        eff = (min(bm, m), min(bn, n))
        if eff in seen or not supported(m, k, n, act, bm, bn, itemsize):
            continue
        seen.add(eff)
        out.append((bm, bn))
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def matmul_bias_act(a, w, b, act="relu", block_m=512, block_n=256):
    """``act(a @ w.T + b)`` with the epilogue fused into the matmul tile.

    a: (M, K), w: (N, K), b: (N,); output keeps ``a.dtype``, epilogue math
    in f32 from the MXU accumulator. Callers gate with ``supported()``;
    ``block_m``/``block_n`` are the autotuner's schedule axis (defaults =
    the planner-default tiling). Interpret mode engages automatically
    off-TPU (parity tests on CPU).
    """
    return _fwd_call(a, w, b, act, block_m, block_n, _interpret_mode())


def _mba_fwd(a, w, b, act, block_m, block_n):
    y = _fwd_call(a, w, b, act, block_m, block_n, _interpret_mode())
    return y, (a, w, b, y)


def _mba_bwd(act, block_m, block_n, saved, dy):
    a, w, b, y = saved
    dpre = dy.astype(jnp.float32) * ACTIVATIONS[act][1](
        y.astype(jnp.float32))
    dpre_c = dpre.astype(a.dtype)
    da = jax.lax.dot_general(dpre_c, w, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32).astype(a.dtype)
    dw = jax.lax.dot_general(dpre_c, a, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32).astype(w.dtype)
    db = jnp.sum(dpre, axis=0)
    return da, dw, db.astype(b.dtype)


matmul_bias_act.defvjp(_mba_fwd, _mba_bwd)
