"""Operator library: pure-JAX implementations behind a single registry.

The TPU-native replacement for the reference's src/operator/ (45.7k LoC of
C++/CUDA, SURVEY.md §2.3): kernels become jnp/lax expressions XLA fuses and
tiles onto the MXU/VPU, so each op is a few lines. The registry (registry.py)
is the single source of truth for both the imperative NDArray frontend and the
symbolic Symbol frontend, like the NNVM registry was for the reference.
"""
from . import registry
from .registry import AttrSpec, OpDef, get_op, has_op, list_ops, parse_attrs, register
from . import infer_meta  # per-op shape/dtype metadata for analysis passes

# importing these modules populates the registry
from . import elemwise  # noqa: F401
from . import broadcast_reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import nn  # noqa: F401
from . import sample  # noqa: F401
from . import sequence  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import ctc  # noqa: F401
from . import rnn  # noqa: F401
from . import vision  # noqa: F401
from . import attention  # noqa: F401
from . import custom  # noqa: F401

__all__ = [
    "AttrSpec",
    "OpDef",
    "get_op",
    "has_op",
    "list_ops",
    "parse_attrs",
    "register",
]
