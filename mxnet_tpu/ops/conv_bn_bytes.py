"""Analytic HBM byte accounting for the fused conv+BN kernel stack.

docs/PERF.md pins the ResNet-50 step at the v5e HBM roofline: every path to
>=0.35 MFU is a bytes-cut. This module is the shared byte model behind the
§6/§6b accounting tables, ``bench.py``'s per-step byte report, and the
autotune harness's site list — one place that counts activation crossings so
the projected cut and the measured engage status talk about the same bytes.

Crossing model (per conv+BN site, activation sizes X = B·K·H·W·itemsize
input-side, C = B·N·H'·W'·itemsize output-side, Wt = weight bytes):

forward, unfused (BN -> relu -> conv -> stats):
    xn write (X) + xn read (X) + c write (C) + stats read (C)     = 2X + 2C
forward, fused (prologue + stats epilogue):
    x read (X) + c write (C)                                      =  X +  C
residual adds +3C unfused (read-read-write pass) vs +C fused (the epilogue
streams the other operand).

backward, unfused (XLA; conv cannot consume or produce a fusion,
arXiv:2301.13062 — the cotangent fold, dgrad, wgrad and prologue backward
each cross HBM):
    dc read + c read + dc_eff write (3C) + dc_eff read x2 (dgrad+wgrad, 2C)
    + xn read (X, wgrad) + dxn write + dxn read (2X)
    + x read (X, dscale) + dx write (X)                           = 5C + 5X
backward, fused (one Pallas dgrad+wgrad kernel, docs/PERF.md §6b):
    dc read + c read (2C) + x read (X) + dx write (X)             = 2C + 2X
    (+C dres write when the residual cotangent must materialize;
     the stash policy adds one X write forward + one X read backward)

Known optimism: every term tied to a revisited block index is a LOWER
bound by the stripe count (1 for most ResNet shapes, up to 4 for the
widest). On the write side that is the stashed-xn block (once per n
stripe) and the dres block (once per k stripe); on the read side the
forward re-streams the x block once per n stripe and the backward
re-streams the dc/c blocks once per k stripe — so the fused terms here
(X read, 2C reads) are the bn=N / bk=K single-stripe ideal. The headline
totals use the recompute policy (no stash term); read the cut percentages
as that ideal, not a measurement — the WINS table exists precisely
because the engage decision must come from timing, not this model.

Weight traffic (Wt read forward, Wt write backward) is identical on both
paths and small next to the activations; it is included in the totals for
honesty but never in the per-site deltas.
"""
from __future__ import annotations

__all__ = ["resnet50_sites", "site_bytes", "step_byte_model"]


def resnet50_sites(image=224):
    """Every conv+BN site of models/resnet.py resnet-50 as
    ``(kernel, stride, K, N, H, count, res_count)`` — ``res_count`` of the
    ``count`` instances are the bottleneck conv3s the fusion plan defers
    into the block's residual add (the 'pr' contract). 53 convs total; the
    7x7 stem and the three stride-2 3x3s are structurally out
    (``supported()`` false). ``image`` scales the spatial dims from the
    canonical 224 (bench.py runs 64 on CPU); the batch is the caller's
    axis — sites are shape tuples, batch-independent."""
    units = [3, 4, 6, 3]
    filters = [64, 256, 512, 1024, 2048]
    sites = {}

    def add(kernel, stride, K, N, H, res=False):
        H = max(1, H * image // 224)
        key = (kernel, stride, K, N, H)
        cnt, rcnt = sites.get(key, (0, 0))
        sites[key] = (cnt + 1, rcnt + (1 if res else 0))

    add((7, 7), (2, 2), 3, 64, 224)  # stem (reported, never supported)
    H = 56
    for stage, n_unit in enumerate(units):
        stride = 1 if stage == 0 else 2
        nf = filters[stage + 1]
        K_in = filters[stage]
        # unit 1 (dim_match=False)
        add((1, 1), (1, 1), K_in, nf // 4, H)            # conv1
        add((3, 3), (stride, stride), nf // 4, nf // 4, H)  # conv2 (strided)
        Ho = H // stride
        add((1, 1), (1, 1), nf // 4, nf, Ho, res=True)   # conv3 -> skip add
        add((1, 1), (stride, stride), K_in, nf, H)       # shortcut
        H = Ho
        for _ in range(n_unit - 1):
            add((1, 1), (1, 1), nf, nf // 4, H)
            add((3, 3), (1, 1), nf // 4, nf // 4, H)
            add((1, 1), (1, 1), nf // 4, nf, H, res=True)
    total = sum(c for c, _ in sites.values())
    assert total == 53, total
    return [(k, s, K, N, H, c, r)
            for (k, s, K, N, H), (c, r) in sorted(sites.items())]


def site_bytes(kernel, stride, K, N, H, batch, itemsize=2, res=False,
               stash=False):
    """Per-site HBM bytes under the crossing model (module docstring):
    dict with fwd/bwd x unfused/fused byte counts plus the weight bytes."""
    Ho = (H + stride[0] - 1) // stride[0]
    Wo = (H + stride[1] - 1) // stride[1]
    X = batch * K * H * H * itemsize
    C = batch * N * Ho * Wo * itemsize
    Wt = N * K * kernel[0] * kernel[1] * itemsize
    fwd_unfused = 2 * X + 2 * C + Wt + (3 * C if res else 0)
    fwd_fused = X + C + Wt + (C if res else 0) + (X if stash else 0)
    bwd_unfused = 5 * C + 5 * X + Wt
    bwd_fused = 2 * C + 2 * X + Wt + (C if res else 0) + (X if stash else 0)
    return {"X": X, "C": C, "Wt": Wt,
            "fwd_unfused": fwd_unfused, "fwd_fused": fwd_fused,
            "bwd_unfused": bwd_unfused, "bwd_fused": bwd_fused}


def step_byte_model(batch, image=224, itemsize=2):
    """Aggregate the crossing model over every *supported* ResNet-50 site:
    projected activation bytes per training step for the three engage
    levels the stack can be in. Unsupported sites (stem, strided 3x3s)
    contribute their unfused bytes to every total — the model never counts
    a cut the kernel cannot make."""
    from .pallas_conv_bn import supported

    tot = {"unfused": 0, "fused_fwd": 0, "fused_fwd_bwd": 0}
    for kernel, stride, K, N, H, count, res_count in resnet50_sites(
            image=image):
        for is_res, cnt in ((False, count - res_count), (True, res_count)):
            if not cnt:
                continue
            b = site_bytes(kernel, stride, K, N, H, batch,
                           itemsize=itemsize, res=is_res)
            ok = supported((batch, K, H, H), (N, K) + kernel, stride,
                           itemsize=itemsize, prologue=True, res=is_res)
            unf = b["fwd_unfused"] + b["bwd_unfused"]
            tot["unfused"] += cnt * unf
            tot["fused_fwd"] += cnt * (
                (b["fwd_fused"] + b["bwd_unfused"]) if ok else unf)
            tot["fused_fwd_bwd"] += cnt * (
                (b["fwd_fused"] + b["bwd_fused"]) if ok else unf)
    gb = {k: round(v / 1e9, 2) for k, v in tot.items()}
    gb["cut_fwd_pct"] = round(100 * (1 - tot["fused_fwd"] / tot["unfused"]), 1)
    gb["cut_fwd_bwd_pct"] = round(
        100 * (1 - tot["fused_fwd_bwd"] / tot["unfused"]), 1)
    return gb
