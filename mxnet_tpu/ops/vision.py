"""Vision / detection operators.

Counterparts of the reference's src/operator/{roi_pooling, spatial_transformer,
grid_generator, bilinear_sampler, crop, correlation}.cc and
src/operator/contrib/{multibox_prior, multibox_target, multibox_detection,
proposal, fft, count_sketch}.cc — the op set behind the SSD and RCNN configs.

TPU-first design notes: every op is a static-shaped jnp/lax composition (no
data-dependent shapes — candidates are masked, not filtered, so XLA can tile);
ROI pooling uses bin masks over the feature map instead of per-bin scalar
loops; NMS is a fixed-trip-count ``lax.fori_loop`` over score-sorted slots.
Differentiable paths (ROIPooling, BilinearSampler, SpatialTransformer, Crop,
fft) get their gradients from JAX; target-assignment ops (MultiBox*, Proposal)
are label machinery with no tangent, like the reference's backward-is-zero
kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import AttrSpec, register

__all__ = []


# ------------------------------------------------------------------ helpers
def _corner_iou(a, b):
    """IoU between box sets a (N,4) and b (M,4), corner layout → (N,M)."""
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[None, :, 0], b[None, :, 1], b[None, :, 2], b[None, :, 3]
    ix = jnp.maximum(0.0, jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1))
    iy = jnp.maximum(0.0, jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1))
    inter = ix * iy
    area_a = jnp.maximum(0.0, ax2 - ax1) * jnp.maximum(0.0, ay2 - ay1)
    area_b = jnp.maximum(0.0, bx2 - bx1) * jnp.maximum(0.0, by2 - by1)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


# --------------------------------------------------------------- ROIPooling
@register(
    "ROIPooling",
    attrs={
        "pooled_size": AttrSpec("shape", required=True),
        "spatial_scale": AttrSpec("float", required=True),
    },
    input_names=("data", "rois"),
)
def _roi_pooling(attrs, data, rois):
    """Max-pool each ROI onto a fixed grid (reference: roi_pooling.cc).
    rois: (R, 5) = [batch_index, x1, y1, x2, y2] in image coords."""
    PH, PW = (int(s) for s in attrs["pooled_size"])
    scale = attrs["spatial_scale"]
    N, C, H, W = data.shape

    def one_roi(roi):
        img = jnp.take(data, roi[0].astype("int32"), axis=0)  # (C,H,W)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = roi_h / PH
        bin_w = roi_w / PW
        ph = jnp.arange(PH, dtype=data.dtype)
        pw = jnp.arange(PW, dtype=data.dtype)
        hstart = jnp.clip(jnp.floor(ph * bin_h) + y1, 0, H)
        hend = jnp.clip(jnp.ceil((ph + 1) * bin_h) + y1, 0, H)
        wstart = jnp.clip(jnp.floor(pw * bin_w) + x1, 0, W)
        wend = jnp.clip(jnp.ceil((pw + 1) * bin_w) + x1, 0, W)
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)
        my = (ys[None, :] >= hstart[:, None]) & (ys[None, :] < hend[:, None])  # (PH,H)
        mx = (xs[None, :] >= wstart[:, None]) & (xs[None, :] < wend[:, None])  # (PW,W)
        mask = my[:, None, :, None] & mx[None, :, None, :]  # (PH,PW,H,W)
        neg = jnp.asarray(-jnp.inf, data.dtype)
        big = jnp.where(mask[:, :, None, :, :], img[None, None], neg)
        out = big.max(axis=(3, 4))  # (PH,PW,C)
        empty = ~mask.any(axis=(2, 3))
        out = jnp.where(empty[:, :, None], 0.0, out)
        return jnp.transpose(out, (2, 0, 1))  # (C,PH,PW)

    return jax.vmap(one_roi)(rois.astype(data.dtype))


# --------------------------------------------------------- BilinearSampler
def _bilinear_sample(data, gx, gy):
    """Sample data (C,H,W) at normalized grid coords gx,gy ∈ [-1,1] (Ho,Wo),
    zero outside the boundary (reference: bilinear_sampler.cc)."""
    C, H, W = data.shape
    x = (gx + 1.0) * (W - 1) / 2.0
    y = (gy + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    x1 = x0 + 1
    y1 = y0 + 1

    def gather(yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype("int32")
        xi = jnp.clip(xx, 0, W - 1).astype("int32")
        valid = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        vals = data[:, yi, xi]  # (C,Ho,Wo)
        return jnp.where(valid[None], vals, 0.0)

    wa = (x1 - x) * (y1 - y)
    wb = (x1 - x) * (y - y0)
    wc = (x - x0) * (y1 - y)
    wd = (x - x0) * (y - y0)
    out = (wa[None] * gather(y0, x0) + wb[None] * gather(y1, x0)
           + wc[None] * gather(y0, x1) + wd[None] * gather(y1, x1))
    return out


@register("BilinearSampler", attrs={}, input_names=("data", "grid"))
def _bilinear_sampler(attrs, data, grid):
    """data (N,C,H,W), grid (N,2,Ho,Wo) with (x,y) in [-1,1]."""
    return jax.vmap(lambda d, g: _bilinear_sample(d, g[0], g[1]))(data, grid)


# ------------------------------------------------------------ GridGenerator
@register(
    "GridGenerator",
    attrs={
        "transform_type": AttrSpec("str", required=True),
        "target_shape": AttrSpec("shape", default=(0, 0)),
    },
)
def _grid_generator(attrs, data):
    """affine: data (N,6) θ → sampling grid (N,2,H,W); warp: data (N,2,H,W)
    flow → identity + normalized flow (reference: grid_generator.cc)."""
    tt = attrs["transform_type"]
    if tt == "affine":
        H, W = (int(s) for s in attrs["target_shape"])
        ys, xs = jnp.meshgrid(jnp.linspace(-1, 1, H), jnp.linspace(-1, 1, W), indexing="ij")
        ones = jnp.ones_like(xs)
        base = jnp.stack([xs, ys, ones], 0).reshape(3, -1).astype(data.dtype)  # (3,HW)
        theta = data.reshape(-1, 2, 3)
        grid = jnp.einsum("nij,jk->nik", theta, base)  # (N,2,HW)
        return grid.reshape(-1, 2, H, W)
    if tt == "warp":
        N, _, H, W = data.shape
        ys, xs = jnp.meshgrid(jnp.arange(H, dtype=data.dtype),
                              jnp.arange(W, dtype=data.dtype), indexing="ij")
        gx = (xs[None] + data[:, 0]) * 2.0 / max(W - 1, 1) - 1.0
        gy = (ys[None] + data[:, 1]) * 2.0 / max(H - 1, 1) - 1.0
        return jnp.stack([gx, gy], axis=1)
    raise ValueError("GridGenerator: unknown transform_type %r" % tt)


# -------------------------------------------------------- SpatialTransformer
@register(
    "SpatialTransformer",
    attrs={
        "target_shape": AttrSpec("shape", required=True),
        "transform_type": AttrSpec("str", default="affine"),
        "sampler_type": AttrSpec("str", default="bilinear"),
    },
    input_names=("data", "loc"),
)
def _spatial_transformer(attrs, data, loc):
    """Affine grid from loc (N,6) + bilinear sampling of data
    (reference: spatial_transformer.cc)."""
    H, W = (int(s) for s in attrs["target_shape"])
    ys, xs = jnp.meshgrid(jnp.linspace(-1, 1, H), jnp.linspace(-1, 1, W), indexing="ij")
    ones = jnp.ones_like(xs)
    base = jnp.stack([xs, ys, ones], 0).reshape(3, -1).astype(data.dtype)
    theta = loc.reshape(-1, 2, 3)
    grid = jnp.einsum("nij,jk->nik", theta, base).reshape(-1, 2, H, W)
    return jax.vmap(lambda d, g: _bilinear_sample(d, g[0], g[1]))(data, grid)


# --------------------------------------------------------------------- Crop
def _crop_names(attrs):
    return ["data", "crop_like"] if int(attrs.get("num_args", 1)) > 1 else ["data"]


@register(
    "Crop",
    attrs={
        "num_args": AttrSpec("int", default=1),
        "offset": AttrSpec("shape", default=(0, 0)),
        "h_w": AttrSpec("shape", default=(0, 0)),
        "center_crop": AttrSpec("bool", default=False),
    },
    input_names=_crop_names,
)
def _crop(attrs, data, crop_like=None):
    """Crop data's spatial dims to h_w (or crop_like's) at offset / centered
    (reference: crop.cc)."""
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = (int(s) for s in attrs["h_w"])
    H, W = data.shape[2], data.shape[3]
    if attrs["center_crop"]:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = (int(s) for s in attrs["offset"])
    return data[:, :, oy : oy + th, ox : ox + tw]


# ------------------------------------------------------------ MultiBoxPrior
@register(
    "_contrib_MultiBoxPrior",
    attrs={
        "sizes": AttrSpec("ftuple", default=(1.0,)),
        "ratios": AttrSpec("ftuple", default=(1.0,)),
        "clip": AttrSpec("bool", default=False),
        "steps": AttrSpec("ftuple", default=(-1.0, -1.0)),
        "offsets": AttrSpec("ftuple", default=(0.5, 0.5)),
    },
    aliases=("MultiBoxPrior",),
)
def _multibox_prior(attrs, data):
    """Anchor boxes per feature-map pixel (reference: contrib/multibox_prior.cc).
    Output (1, H*W*A, 4) corner boxes in [0,1] coords;
    A = len(sizes) + len(ratios) - 1."""
    H, W = data.shape[2], data.shape[3]
    sizes = [float(s) for s in attrs["sizes"]]
    ratios = [float(r) for r in attrs["ratios"]]
    step_y, step_x = (float(s) for s in attrs["steps"])
    off_y, off_x = (float(o) for o in attrs["offsets"])
    if step_y <= 0:
        step_y = 1.0 / H
    if step_x <= 0:
        step_x = 1.0 / W
    cy = (jnp.arange(H, dtype=data.dtype) + off_y) * step_y
    cx = (jnp.arange(W, dtype=data.dtype) + off_x) * step_x
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")  # (H,W)
    whs = []
    for k, s in enumerate(sizes):
        r = ratios[0]
        whs.append((s * np.sqrt(r), s / np.sqrt(r)))
    for r in ratios[1:]:
        s = sizes[0]
        whs.append((s * np.sqrt(r), s / np.sqrt(r)))
    anchors = []
    for w, h in whs:
        anchors.append(jnp.stack(
            [cxg - w / 2, cyg - h / 2, cxg + w / 2, cyg + h / 2], axis=-1))
    out = jnp.stack(anchors, axis=2).reshape(-1, 4)  # (H*W*A, 4)
    if attrs["clip"]:
        out = jnp.clip(out, 0.0, 1.0)
    return out[None]


# ----------------------------------------------------------- MultiBoxTarget
@register(
    "_contrib_MultiBoxTarget",
    attrs={
        "overlap_threshold": AttrSpec("float", default=0.5),
        "ignore_label": AttrSpec("float", default=-1.0),
        "negative_mining_ratio": AttrSpec("float", default=-1.0),
        "negative_mining_thresh": AttrSpec("float", default=0.5),
        "minimum_negative_samples": AttrSpec("int", default=0),
        "variances": AttrSpec("ftuple", default=(0.1, 0.1, 0.2, 0.2)),
    },
    input_names=("anchor", "label", "cls_pred"),
    aliases=("MultiBoxTarget",),
    num_outputs=3,
    output_names=("loc_target", "loc_mask", "cls_target"),
)
def _multibox_target(attrs, anchor, label, cls_pred):
    """Assign ground truth to anchors (reference: contrib/multibox_target.cc).
    anchor (1,N,4); label (B,M,5) rows [cls,x1,y1,x2,y2], cls<0 = pad;
    cls_pred (B, num_cls+1, N). Outputs: loc_target (B,4N), loc_mask (B,4N),
    cls_target (B,N) with 0 = background, k+1 = class k, and — under hard
    negative mining — ignore_label for unmined negatives
    (multibox_target.cc:162-229)."""
    anchors = anchor[0]  # (N,4)
    N = anchors.shape[0]
    v = attrs["variances"]
    thresh = attrs["overlap_threshold"]
    mine_ratio = attrs["negative_mining_ratio"]
    mine_thresh = attrs["negative_mining_thresh"]
    min_neg = attrs["minimum_negative_samples"]
    ignore = attrs["ignore_label"]

    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one(lab, preds):
        valid = lab[:, 0] >= 0  # (M,)
        gt = lab[:, 1:5]
        iou = _corner_iou(anchors, gt)  # (N,M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)  # (N,)
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= thresh
        # force-match: each valid gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)  # (M,)
        forced = jnp.zeros((N,), bool).at[best_anchor].set(valid)
        forced_gt = jnp.zeros((N,), "int32").at[best_anchor].set(
            jnp.arange(gt.shape[0], dtype="int32"))
        use_forced = forced
        gt_idx = jnp.where(use_forced, forced_gt, best_gt)
        matched = matched | use_forced

        g = gt[gt_idx]  # (N,4)
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / v[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / v[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / v[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / v[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=1)  # (N,4)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0)
        loc_m = jnp.where(matched[:, None], 1.0, 0.0) * jnp.ones((N, 4), anchors.dtype)

        if mine_ratio > 0:
            # hard negative mining: keep the (ratio × positives) unmatched
            # anchors whose background probability is LOWEST (hardest), mark
            # the rest ignore_label so the class loss skips them
            num_pos = jnp.sum(matched)
            num_neg = jnp.minimum(
                (mine_ratio * num_pos).astype("int32"), N - num_pos)
            num_neg = jnp.maximum(num_neg, min_neg)
            prob_bg = jax.nn.softmax(preds, axis=0)[0]  # (N,)
            eligible = (~matched) & (best_iou < mine_thresh)
            score = jnp.where(eligible, -prob_bg, -jnp.inf)
            order = jnp.argsort(-score)  # hardest first
            rank = jnp.zeros((N,), "int32").at[order].set(jnp.arange(N, dtype="int32"))
            neg = eligible & (rank < num_neg)
            cls_t = jnp.where(
                matched, lab[gt_idx, 0] + 1.0,
                jnp.where(neg, 0.0, jnp.asarray(ignore, anchors.dtype)))
        else:
            cls_t = jnp.where(matched, lab[gt_idx, 0] + 1.0, 0.0)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    # training targets are constants (the reference op has no backward):
    # without this, the loc MakeLoss would backprop into cls_pred through
    # the mining softmax
    return (jax.lax.stop_gradient(loc_t), jax.lax.stop_gradient(loc_m),
            jax.lax.stop_gradient(cls_t))


# -------------------------------------------------------- MultiBoxDetection
def _nms_mask(boxes, scores, keep_init, nms_threshold, topk):
    """Greedy NMS over score-sorted boxes; returns keep mask (N,) bool.
    Fixed trip count (topk) so the loop compiles once."""
    N = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    keep = keep_init[order]

    def body(i, keep):
        cur_valid = keep[i]
        iou = _corner_iou(boxes_s[i][None], boxes_s)[0]  # (N,)
        suppress = (iou > nms_threshold) & (jnp.arange(N) > i) & cur_valid
        return keep & ~suppress

    keep = jax.lax.fori_loop(0, min(topk, N), body, keep)
    inv = jnp.zeros((N,), "int32").at[order].set(jnp.arange(N, dtype="int32"))
    return keep[inv]


@register(
    "_contrib_MultiBoxDetection",
    attrs={
        "clip": AttrSpec("bool", default=True),
        "threshold": AttrSpec("float", default=0.01),
        "background_id": AttrSpec("int", default=0),
        "nms_threshold": AttrSpec("float", default=0.5),
        "force_suppress": AttrSpec("bool", default=False),
        "variances": AttrSpec("ftuple", default=(0.1, 0.1, 0.2, 0.2)),
        "nms_topk": AttrSpec("int", default=-1),
    },
    input_names=("cls_prob", "loc_pred", "anchor"),
    aliases=("MultiBoxDetection",),
)
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """Decode + NMS (reference: contrib/multibox_detection.cc).
    cls_prob (B,num_cls+1,N), loc_pred (B,4N), anchor (1,N,4) →
    (B,N,6) rows [cls_id, score, x1,y1,x2,y2]; suppressed rows cls_id=-1."""
    anchors = anchor[0]
    N = anchors.shape[0]
    v = attrs["variances"]
    bg = int(attrs["background_id"])
    topk = attrs["nms_topk"] if attrs["nms_topk"] > 0 else N

    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one(probs, loc):
        loc = loc.reshape(N, 4)
        cx = loc[:, 0] * v[0] * aw + acx
        cy = loc[:, 1] * v[1] * ah + acy
        w = jnp.exp(loc[:, 2] * v[2]) * aw
        h = jnp.exp(loc[:, 3] * v[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)
        if attrs["clip"]:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        masked = probs.at[bg].set(-1.0)
        cls_id = jnp.argmax(masked, axis=0)  # (N,)
        score = jnp.max(masked, axis=0)
        valid = score > attrs["threshold"]
        keep = _nms_mask(boxes, jnp.where(valid, score, -1.0), valid,
                         attrs["nms_threshold"], topk)
        out_id = jnp.where(keep, cls_id.astype(boxes.dtype) - (1.0 if bg == 0 else 0.0), -1.0)
        return jnp.concatenate([out_id[:, None], score[:, None], boxes], axis=1)

    return jax.vmap(one)(cls_prob, loc_pred)


# ------------------------------------------------------------------ Proposal
@register(
    "_contrib_Proposal",
    attrs={
        "rpn_pre_nms_top_n": AttrSpec("int", default=6000),
        "rpn_post_nms_top_n": AttrSpec("int", default=300),
        "threshold": AttrSpec("float", default=0.7),
        "rpn_min_size": AttrSpec("int", default=16),
        "scales": AttrSpec("ftuple", default=(4.0, 8.0, 16.0, 32.0)),
        "ratios": AttrSpec("ftuple", default=(0.5, 1.0, 2.0)),
        "feature_stride": AttrSpec("int", default=16),
        "output_score": AttrSpec("bool", default=False),
        "iou_loss": AttrSpec("bool", default=False),
    },
    input_names=("cls_prob", "bbox_pred", "im_info"),
    aliases=("Proposal",),
)
def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposal generation (reference: contrib/proposal.cc).
    cls_prob (B, 2A, H, W), bbox_pred (B, 4A, H, W), im_info (B,3)
    → rois (B*post_nms, 5) [batch_idx, x1,y1,x2,y2]."""
    B, _, H, W = cls_prob.shape
    scales = [float(s) for s in attrs["scales"]]
    ratios = [float(r) for r in attrs["ratios"]]
    stride = attrs["feature_stride"]
    A = len(scales) * len(ratios)
    post_n = int(attrs["rpn_post_nms_top_n"])

    # base anchors centered on stride/2 (generate_anchors convention)
    base = []
    cx = cy = (stride - 1) / 2.0
    for r in ratios:
        size = stride * stride
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            base.append([cx - (w - 1) / 2, cy - (h - 1) / 2,
                         cx + (w - 1) / 2, cy + (h - 1) / 2])
    base = jnp.asarray(np.array(base, dtype="float32"))  # (A,4)
    sy = jnp.arange(H, dtype="float32") * stride
    sx = jnp.arange(W, dtype="float32") * stride
    syg, sxg = jnp.meshgrid(sy, sx, indexing="ij")
    shift = jnp.stack([sxg, syg, sxg, syg], axis=-1).reshape(-1, 1, 4)  # (HW,1,4)
    anchors = (shift + base[None]).reshape(-1, 4)  # (HW*A,4)
    N = anchors.shape[0]

    def one(probs, deltas, info):
        scores = probs[A:].reshape(A, H, W).transpose(1, 2, 0).reshape(-1)  # fg scores
        d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw / 2
        acy = anchors[:, 1] + ah / 2
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], 1)
        min_size = attrs["rpn_min_size"] * info[2]
        valid = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size)
                 & (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
        scores = jnp.where(valid, scores, -1.0)
        keep = _nms_mask(boxes, scores, valid, attrs["threshold"],
                         min(int(attrs["rpn_pre_nms_top_n"]), N))
        scores = jnp.where(keep, scores, -1.0)
        top_idx = jnp.argsort(-scores)[:post_n]
        return boxes[top_idx], scores[top_idx]

    boxes, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)  # (B,post,4)
    bidx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), post_n).reshape(B, post_n, 1)
    rois = jnp.concatenate([bidx, boxes], axis=2).reshape(B * post_n, 5)
    if attrs["output_score"]:
        return rois, scores.reshape(B * post_n, 1)
    return rois


# ------------------------------------------------------------------ fft/ifft
@register("_contrib_fft", attrs={"compute_size": AttrSpec("int", default=128)},
          aliases=("fft",))
def _fft(attrs, data):
    """FFT along the last axis; output interleaves real/imag (…, 2K)
    (reference: contrib/fft.cc)."""
    out = jnp.fft.fft(data.astype("complex64"), axis=-1)
    stacked = jnp.stack([out.real, out.imag], axis=-1)
    return stacked.reshape(data.shape[:-1] + (2 * data.shape[-1],)).astype(data.dtype)


@register("_contrib_ifft", attrs={"compute_size": AttrSpec("int", default=128)},
          aliases=("ifft",))
def _ifft(attrs, data):
    """Inverse of _contrib_fft: input (…, 2K) interleaved → (…, K) real.
    Matches the reference's unnormalized ifft (contrib/ifft.cc): scaled by K."""
    K = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (K, 2))
    z = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(z.astype("complex64"), axis=-1).real * K
    return out.astype(data.dtype)


# -------------------------------------------------------------- count_sketch
@register(
    "_contrib_count_sketch",
    attrs={"out_dim": AttrSpec("int", required=True),
           "processing_batch_size": AttrSpec("int", default=32)},
    input_names=("data", "h", "s"),
    aliases=("count_sketch",),
)
def _count_sketch(attrs, data, h, s):
    """Count-sketch projection: out[n, h[j]] += s[j]·data[n, j]
    (reference: contrib/count_sketch.cc)."""
    out_dim = int(attrs["out_dim"])
    idx = h.reshape(-1).astype("int32")
    sign = s.reshape(-1).astype(data.dtype)
    vals = data * sign[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return out.at[:, idx].add(vals)


# --------------------------------------------------------------- Correlation
@register(
    "Correlation",
    attrs={
        "kernel_size": AttrSpec("int", default=1),
        "max_displacement": AttrSpec("int", default=1),
        "stride1": AttrSpec("int", default=1),
        "stride2": AttrSpec("int", default=1),
        "pad_size": AttrSpec("int", default=0),
        "is_multiply": AttrSpec("bool", default=True),
    },
    input_names=("data1", "data2"),
)
def _correlation(attrs, data1, data2):
    """FlowNet correlation layer (reference: correlation.cc). For each
    displacement (dy,dx) in the neighborhood, mean over channels of
    data1·shift(data2) (or |data1−shift|, is_multiply=False)."""
    md = int(attrs["max_displacement"])
    s2 = int(attrs["stride2"])
    pad = int(attrs["pad_size"])
    N, C, H, W = data1.shape
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    disp = range(-md, md + 1, s2)
    outs = []
    Hp, Wp = H + 2 * pad, W + 2 * pad
    for dy in disp:
        for dx in disp:
            shifted = jnp.roll(p2, shift=(-dy, -dx), axis=(2, 3))
            if attrs["is_multiply"]:
                prod = (p1 * shifted).mean(axis=1)
            else:
                prod = jnp.abs(p1 - shifted).mean(axis=1)
            outs.append(prod)
    out = jnp.stack(outs, axis=1)  # (N, D*D, Hp, Wp)
    return out[:, :, pad : Hp - pad, pad : Wp - pad] if pad else out
