"""Fused multi-layer RNN op.

TPU-native replacement for the reference's cuDNN-backed ``RNN`` operator
(src/operator/rnn.cc:34, cudnn_rnn-inl.h): the whole sequence runs inside one
``lax.scan`` per layer, so XLA compiles a single fused loop with the per-step
gate matmuls batched onto the MXU. Weight layout matches FusedRNNCell packing
(python/mxnet/rnn/rnn_cell.py:497): per layer (and per direction), i2h_weight
then h2h_weight; all biases after all weights (i2h_bias, h2h_bias per
layer/direction). Gate order: LSTM [i, f, c, o]; GRU [r, z, n].

Data layout (seq_len, batch, input) — the reference's default TNC.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import AttrSpec, register


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    """Total number of elements in the packed parameter vector."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        size += d * g * state_size * (in_sz + state_size)  # weights
    size += num_layers * d * 2 * g * state_size  # biases
    return size


def _unpack_params(params, num_layers, input_size, state_size, bidirectional, mode):
    """Slice the flat parameter vector into per-layer/direction (Wx, Wh, bx, bh)."""
    g = _gates(mode)
    d = 2 if bidirectional else 1
    out = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        layer_ws = []
        for _ in range(d):
            wx = params[off : off + g * state_size * in_sz].reshape(g * state_size, in_sz)
            off += g * state_size * in_sz
            wh = params[off : off + g * state_size * state_size].reshape(g * state_size, state_size)
            off += g * state_size * state_size
            layer_ws.append([wx, wh])
        out.append(layer_ws)
    for layer in range(num_layers):
        for di in range(d):
            bx = params[off : off + g * state_size]
            off += g * state_size
            bh = params[off : off + g * state_size]
            off += g * state_size
            out[layer][di].extend([bx, bh])
    return out


def _cell_step(mode, state_size):
    """Per-timestep recurrence consuming the PRE-COMPUTED input-side gates
    ``zx_t = x_t @ wx.T + bx`` — only the hidden-side matmul stays inside
    the scan (see _run_layer)."""
    H = state_size

    if mode == "lstm":

        def step(carry, zx_t, wh, bh):
            h, c = carry
            z = zx_t + h @ wh.T + bh
            i = jax.nn.sigmoid(z[:, :H])
            f = jax.nn.sigmoid(z[:, H : 2 * H])
            gg = jnp.tanh(z[:, 2 * H : 3 * H])
            o = jax.nn.sigmoid(z[:, 3 * H :])
            c_new = f * c + i * gg
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new

    elif mode == "gru":

        def step(carry, zx_t, wh, bh):
            (h,) = carry
            zh = h @ wh.T + bh
            r = jax.nn.sigmoid(zx_t[:, :H] + zh[:, :H])
            z = jax.nn.sigmoid(zx_t[:, H : 2 * H] + zh[:, H : 2 * H])
            n = jnp.tanh(zx_t[:, 2 * H :] + r * zh[:, 2 * H :])
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new

    else:

        def step(carry, zx_t, wh, bh):
            (h,) = carry
            z = zx_t + h @ wh.T + bh
            h_new = jnp.maximum(z, 0) if mode == "rnn_relu" else jnp.tanh(z)
            return (h_new,), h_new

    return step


def _run_layer(mode, state_size, x, h0, c0, wx, wh, bx, bh, reverse=False):
    """One recurrent layer. The input-side gate GEMM has no sequential
    dependency, so it is hoisted OUT of the scan as one (T*B, I) x (I, G*H)
    matmul — T MXU-starved (B, I) matmuls become a single large one and the
    loop keeps only the irreducibly-sequential h @ wh.T (the cuDNN fused-RNN
    economics, reference src/operator/cudnn_rnn-inl.h; docs/PERF.md §6)."""
    step = _cell_step(mode, state_size)
    carry0 = (h0, c0) if mode == "lstm" else (h0,)
    # x: (T, B, I) -> zx: (T, B, G*H), one GEMM over all timesteps
    zx_all = x @ wx.T + bx

    def scan_fn(carry, zx_t):
        return step(carry, zx_t, wh, bh)

    carry, ys = jax.lax.scan(scan_fn, carry0, zx_all, reverse=reverse)
    return carry, ys


def _rnn_names(attrs):
    names = ["data", "parameters", "state"]
    if attrs.get("mode") == "lstm":
        names.append("state_cell")
    return names


def _rnn_nout(attrs):
    if not attrs.get("state_outputs"):
        return 1
    return 3 if attrs.get("mode") == "lstm" else 2


@register(
    "RNN",
    attrs={
        "state_size": AttrSpec("int", required=True),
        "num_layers": AttrSpec("int", required=True),
        "bidirectional": AttrSpec("bool", default=False),
        "mode": AttrSpec("str", required=True),
        "p": AttrSpec("float", default=0.0),
        "state_outputs": AttrSpec("bool", default=False),
    },
    input_names=_rnn_names,
    num_outputs=_rnn_nout,
    output_names=lambda a: ["output", "state_output", "statecell_output"][: _rnn_nout(a)],
    needs_rng=True,
    needs_train_flag=True,
)
def _rnn(attrs, data, parameters, state, state_cell=None, is_train=False, rng=None):
    mode = attrs["mode"]
    H = attrs["state_size"]
    L = attrs["num_layers"]
    bidir = bool(attrs["bidirectional"])
    d = 2 if bidir else 1
    T, N, I = data.shape
    layers = _unpack_params(parameters, L, I, H, bidir, mode)

    x = data
    h_out, c_out = [], []
    for layer in range(L):
        if is_train and attrs["p"] > 0 and layer > 0 and rng is not None:
            rng, sub = jax.random.split(rng)
            keep = 1.0 - attrs["p"]
            mask = jax.random.bernoulli(sub, keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)
        dir_outs = []
        for di in range(d):
            wx, wh, bx, bh = layers[layer][di]
            h0 = state[layer * d + di]
            c0 = state_cell[layer * d + di] if mode == "lstm" else None
            carry, ys = _run_layer(mode, H, x, h0, c0, wx, wh, bx, bh, reverse=(di == 1))
            dir_outs.append(ys)
            h_out.append(carry[0])
            if mode == "lstm":
                c_out.append(carry[1])
        x = dir_outs[0] if d == 1 else jnp.concatenate(dir_outs, axis=-1)

    outs = [x]
    if attrs["state_outputs"]:
        outs.append(jnp.stack(h_out, axis=0))
        if mode == "lstm":
            outs.append(jnp.stack(c_out, axis=0))
    return tuple(outs) if len(outs) > 1 else outs[0]
