"""Random sampling ops.

Covers the reference's src/operator/tensor/sample_op.* (uniform, normal, gamma,
exponential, poisson, negative_binomial, generalized_negative_binomial). The
reference draws from a per-device mshadow::Random resource
(ResourceRequest::kRandom, include/mxnet/resource.h:20-25); here every sampler
takes a JAX PRNG key threaded by the dispatch layer — functional, reproducible,
and SPMD-safe (keys can be split per mesh shard).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import AttrSpec, register


def _sample_attrs(**extra):
    base = {
        "shape": AttrSpec("shape", default=()),
        "dtype": AttrSpec("dtype", default=np.float32),
        "ctx": AttrSpec("str", default=""),
    }
    base.update(extra)
    return base


def _reg_sampler(name, attr_extra, draw, aliases=()):
    def fn(attrs, rng=None):
        shape = tuple(attrs["shape"]) or (1,)
        dtype = attrs["dtype"]
        if rng is None:
            rng = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        return draw(rng, shape, dtype, attrs)

    fn.__doc__ = "Draw samples (reference: tensor/sample_op.cc %s)." % name
    register(
        name, attrs=_sample_attrs(**attr_extra), input_names=(), needs_rng=True, aliases=aliases
    )(fn)


_reg_sampler(
    "random_uniform",
    {"low": AttrSpec("float", default=0.0), "high": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: jax.random.uniform(k, s, dtype=d, minval=a["low"], maxval=a["high"]),
    aliases=("_sample_uniform", "uniform"),
)
_reg_sampler(
    "random_normal",
    {"loc": AttrSpec("float", default=0.0), "scale": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: a["loc"] + a["scale"] * jax.random.normal(k, s, dtype=d),
    aliases=("_sample_normal", "normal"),
)
# NOTE: canonical name is random_gamma — the bare name "gamma" is the unary
# Γ(x) op in elemwise.py, exactly as in the reference (elemwise_unary_op.cc
# vs sample_op.cc); the registry now rejects such collisions.
_reg_sampler(
    "random_gamma",
    {"alpha": AttrSpec("float", default=1.0), "beta": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: a["beta"] * jax.random.gamma(k, a["alpha"], s, dtype=d),
    aliases=("_sample_gamma",),
)
_reg_sampler(
    "random_exponential",
    {"lam": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: jax.random.exponential(k, s, dtype=d) / a["lam"],
    aliases=("_sample_exponential", "exponential"),
)
_reg_sampler(
    "random_poisson",
    {"lam": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: jax.random.poisson(k, a["lam"], s).astype(d),
    aliases=("_sample_poisson", "poisson"),
)


def _neg_binomial(k, s, d, a):
    kk, p = a["k"], a["p"]
    k1, k2 = jax.random.split(k)
    lam = jax.random.gamma(k1, kk, s) * (1.0 - p) / p
    return jax.random.poisson(k2, lam, s).astype(d)


_reg_sampler(
    "random_negative_binomial",
    {"k": AttrSpec("int", default=1), "p": AttrSpec("float", default=1.0)},
    _neg_binomial,
    aliases=("_sample_negbinomial", "negative_binomial"),
)


def _gen_neg_binomial(k, s, d, a):
    mu, alpha = a["mu"], a["alpha"]
    if alpha <= 0:
        return jax.random.poisson(k, mu, s).astype(d)
    k1, k2 = jax.random.split(k)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, s) * (1.0 - p) / p
    return jax.random.poisson(k2, lam, s).astype(d)


_reg_sampler(
    "random_generalized_negative_binomial",
    {"mu": AttrSpec("float", default=1.0), "alpha": AttrSpec("float", default=1.0)},
    _gen_neg_binomial,
    aliases=("_sample_gennegbinomial", "generalized_negative_binomial"),
)
