"""Random sampling ops.

Covers the reference's src/operator/tensor/sample_op.* (uniform, normal, gamma,
exponential, poisson, negative_binomial, generalized_negative_binomial). The
reference draws from a per-device mshadow::Random resource
(ResourceRequest::kRandom, include/mxnet/resource.h:20-25); here every sampler
takes a JAX PRNG key threaded by the dispatch layer — functional, reproducible,
and SPMD-safe (keys can be split per mesh shard).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import AttrSpec, register


def _sample_attrs(**extra):
    base = {
        "shape": AttrSpec("shape", default=()),
        "dtype": AttrSpec("dtype", default=np.float32),
        "ctx": AttrSpec("str", default=""),
    }
    base.update(extra)
    return base


def _reg_sampler(name, attr_extra, draw, aliases=()):
    def fn(attrs, rng=None):
        shape = tuple(attrs["shape"]) or (1,)
        dtype = attrs["dtype"]
        if rng is None:
            rng = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        return draw(rng, shape, dtype, attrs)

    fn.__doc__ = "Draw samples (reference: tensor/sample_op.cc %s)." % name
    register(
        name, attrs=_sample_attrs(**attr_extra), input_names=(), needs_rng=True, aliases=aliases
    )(fn)


_reg_sampler(
    "random_uniform",
    {"low": AttrSpec("float", default=0.0), "high": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: jax.random.uniform(k, s, dtype=d, minval=a["low"], maxval=a["high"]),
    aliases=("_sample_uniform", "uniform"),
)
_reg_sampler(
    "random_normal",
    {"loc": AttrSpec("float", default=0.0), "scale": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: a["loc"] + a["scale"] * jax.random.normal(k, s, dtype=d),
    aliases=("_sample_normal", "normal"),
)
# NOTE: canonical name is random_gamma — the bare name "gamma" is the unary
# Γ(x) op in elemwise.py, exactly as in the reference (elemwise_unary_op.cc
# vs sample_op.cc); the registry now rejects such collisions.
_reg_sampler(
    "random_gamma",
    {"alpha": AttrSpec("float", default=1.0), "beta": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: a["beta"] * jax.random.gamma(k, a["alpha"], s, dtype=d),
    aliases=("_sample_gamma",),
)
_reg_sampler(
    "random_exponential",
    {"lam": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: jax.random.exponential(k, s, dtype=d) / a["lam"],
    aliases=("_sample_exponential", "exponential"),
)
_reg_sampler(
    "random_poisson",
    {"lam": AttrSpec("float", default=1.0)},
    lambda k, s, d, a: jax.random.poisson(k, a["lam"], s).astype(d),
    aliases=("_sample_poisson", "poisson"),
)


def _neg_binomial(k, s, d, a):
    kk, p = a["k"], a["p"]
    k1, k2 = jax.random.split(k)
    lam = jax.random.gamma(k1, kk, s) * (1.0 - p) / p
    return jax.random.poisson(k2, lam, s).astype(d)


_reg_sampler(
    "random_negative_binomial",
    {"k": AttrSpec("int", default=1), "p": AttrSpec("float", default=1.0)},
    _neg_binomial,
    aliases=("_sample_negbinomial", "negative_binomial"),
)


def _gen_neg_binomial(k, s, d, a):
    mu, alpha = a["mu"], a["alpha"]
    if alpha <= 0:
        return jax.random.poisson(k, mu, s).astype(d)
    k1, k2 = jax.random.split(k)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, s) * (1.0 - p) / p
    return jax.random.poisson(k2, lam, s).astype(d)


_reg_sampler(
    "random_generalized_negative_binomial",
    {"mu": AttrSpec("float", default=1.0), "alpha": AttrSpec("float", default=1.0)},
    _gen_neg_binomial,
    aliases=("_sample_gennegbinomial", "generalized_negative_binomial"),
)


# ---------------------------------------------------------------- multisample
# Reference: src/operator/tensor/multisample_op.* — per-row distribution
# parameters come as input arrays of shape (n,) (or (n, m)); output is
# params.shape + shape. TPU-native: one vectorized draw with the parameter
# arrays broadcast against the trailing sample axes (no per-row loop — the
# whole batch lowers to a single fused XLA kernel).


def _bshape(param, shape):
    # empty shape attr → output shape == params shape (reference:
    # tensor/multisample_op.h default TShape)
    return tuple(param.shape) + tuple(shape)


def _expand(param, shape):
    return jnp.reshape(param, tuple(param.shape) + (1,) * len(tuple(shape)))


def _reg_multisample(name, input_names, draw):
    def fn(attrs, *inputs, rng=None):
        shape = tuple(attrs["shape"])
        dtype = attrs["dtype"] if attrs["dtype"] is not None else inputs[0].dtype
        if rng is None:
            rng = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
        return draw(rng, shape, dtype, *inputs)

    fn.__doc__ = ("Per-row parameterized samples (reference: "
                  "tensor/multisample_op.cc %s)." % name)
    register(
        name,
        attrs={"shape": AttrSpec("shape", default=()),
               "dtype": AttrSpec("dtype", default=None)},
        input_names=input_names,
        needs_rng=True,
    )(fn)


_reg_multisample(
    "sample_uniform", ("low", "high"),
    lambda k, s, d, low, high: _expand(low, s) + (_expand(high, s) - _expand(low, s))
    * jax.random.uniform(k, _bshape(low, s), dtype=d),
)
_reg_multisample(
    "sample_normal", ("mu", "sigma"),
    lambda k, s, d, mu, sigma: _expand(mu, s) + _expand(sigma, s)
    * jax.random.normal(k, _bshape(mu, s), dtype=d),
)
_reg_multisample(
    "sample_gamma", ("alpha", "beta"),
    lambda k, s, d, alpha, beta: _expand(beta, s)
    * jax.random.gamma(k, _expand(alpha, s), _bshape(alpha, s), dtype=d),
)
_reg_multisample(
    "sample_exponential", ("lam",),
    lambda k, s, d, lam: jax.random.exponential(k, _bshape(lam, s), dtype=d)
    / _expand(lam, s),
)
_reg_multisample(
    "sample_poisson", ("lam",),
    lambda k, s, d, lam: jax.random.poisson(k, _expand(lam, s),
                                            _bshape(lam, s)).astype(d or jnp.float32),
)


def _ms_negbinomial(k, s, d, kparam, p):
    k1, k2 = jax.random.split(k)
    lam = jax.random.gamma(k1, _expand(kparam, s), _bshape(kparam, s)) \
        * (1.0 - _expand(p, s)) / _expand(p, s)
    return jax.random.poisson(k2, lam, _bshape(kparam, s)).astype(d or jnp.float32)


_reg_multisample("sample_negative_binomial", ("k", "p"), _ms_negbinomial)


def _ms_gen_negbinomial(k, s, d, mu, alpha):
    k1, k2 = jax.random.split(k)
    r = 1.0 / jnp.maximum(_expand(alpha, s), 1e-8)
    p = r / (r + _expand(mu, s))
    lam = jax.random.gamma(k1, r, _bshape(mu, s)) * (1.0 - p) / p
    return jax.random.poisson(k2, lam, _bshape(mu, s)).astype(d or jnp.float32)


_reg_multisample("sample_generalized_negative_binomial", ("mu", "alpha"),
                 _ms_gen_negbinomial)
