"""Fused conv + BatchNorm Pallas TPU kernel stack (round-5 performance work).

docs/PERF.md's roofline analysis pins the ResNet-50 step at the v5e HBM
roofline (72.3 GB/step at 809 of 819 GB/s): every path to >=0.35 MFU is a
bytes-cut, and the one remaining lever is the hand-fused conv+BN kernel —
the TPU counterpart of the reference's vendor conv kernels
(/root/reference/src/operator/cudnn_convolution-inl.h) behind its published
speed table (example/image-classification/README.md:149-156).

This module is that kernel. For NCHW activations viewed as ``(B, K, H*W)``
(a free reshape — no transposes), one Pallas kernel computes

    c[b, n, hw] = sum_k w[n, k] * xn[b, k, hw]            (1x1 conv = matmul)
    c[b, n, hw] = sum_{k,t} w[t, n, k] * shift_t(xn)[b, k, hw]   (3x3, 9 taps)

with three fusions XLA cannot do (a convolution cannot be a fusion producer):

- **prologue**: ``xn = relu(x * scale + shift)`` applied in VMEM — the
  upstream BatchNorm+ReLU output is never materialized in HBM. In the
  pre-activation ResNet chain (BN -> relu -> Conv, models/resnet.py) this
  deletes one full activation write + read per edge.
- **residual epilogue**: ``c += res`` read tile-wise — the bottleneck-block
  skip add costs no separate read-read-write pass.
- **stats epilogue**: per-channel ``sum(c)`` and ``sum(c^2)`` accumulated
  from the f32 MXU accumulator across the (B,) grid sweep — the downstream
  BatchNorm's statistics pass re-reads nothing.

Layout: grid ``(N/bn, B)`` (channel stripes parallel, batch sweep carries
the stats accumulator); blocks keep the whole HW extent per instance (every
ResNet-50 @224 shape fits VMEM this way — see ``choose_blocks``). The 3x3
taps are static-slice rolls of the VMEM-resident xn tile with
host-precomputed edge masks applied to the dot *result* (a per-column mask
commutes with the contraction over K).

The autodiff boundary is exactly this kernel (``conv_block`` is a
custom_vjp): its backward is ``jax.vjp`` of the equivalent XLA convolution
(the primal conv is dead code and DCE'd; the stats cotangents fold into the
output cotangent as ``dc + ds + 2*c*dq`` using the saved output). All BN
scalar math (mean/var/normalize, moving-stat updates) stays in plain JAX in
the graph pass (executor fusion plan) so gradients flow through it
naturally. Numerics note: the kernel's statistics come from the f32
accumulator *before* the bf16 round of c; XLA's unfused lowering reduces
the rounded activations — they differ at the bf16-epsilon level, inside BN's
eps regime.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["conv_block", "supported", "plan_blocks", "choose_blocks"]

_VMEM_BUDGET = 12 * 1024 * 1024


def choose_blocks(B, K, N, HW, itemsize, taps=1, prologue=False, res=False):
    """Pick the channel-stripe width ``bn`` (largest divisor of N, multiple
    of 8, that keeps the per-instance VMEM working set under budget) for the
    whole-HW tiling. Returns None if no stripe fits."""
    for bn in (512, 256, 128, 64, 32, 16, 8):
        if N % bn:
            continue
        est = (
            2 * K * HW * itemsize          # x tile, double-buffered
            + 2 * bn * HW * itemsize       # c tile, double-buffered
            + bn * HW * 4                  # f32 accumulator
            + taps * bn * K * itemsize     # weight stripe
            + (2 * bn * HW * itemsize if res else 0)  # residual stream, db
            + (K * HW * itemsize if (prologue or taps > 1) else 0)  # xn temp
            + (K * HW * itemsize if taps > 1 else 0)                # shifted temp
            + (taps * HW * 4 if taps > 1 else 0)                    # masks
        )
        if est <= _VMEM_BUDGET:
            return bn
    return None


def plan_blocks(x_shape, w_shape, stride=(1, 1), itemsize=2, prologue=True,
                res=False):
    """The kernel's tiling decision for a concrete call: the channel-stripe
    width ``bn``, or None when this conv cannot (or should not) run on the
    Pallas path. This is the single source of truth — ``supported`` and the
    forward both call it with the SAME flags (itemsize, prologue, residual),
    so a call that passes the gate can never hit an internal assert instead
    of the XLA fallback."""
    if len(x_shape) != 4 or len(w_shape) != 4 or itemsize > 4:
        return None
    B, K, H, W = x_shape
    N, K2, kh, kw = w_shape
    if K != K2:
        return None
    if (kh, kw) == (1, 1):
        if stride[0] != stride[1] or stride[0] not in (1, 2):
            return None
        H, W = H // stride[0], W // stride[1]
        taps = 1
    elif (kh, kw) == (3, 3):
        if stride != (1, 1):
            return None
        taps = 9
    else:
        return None
    if K % 8 or H * W < 8:
        return None
    return choose_blocks(B, K, N, H * W, itemsize, taps=taps,
                         prologue=prologue, res=res)


def supported(x_shape, w_shape, stride=(1, 1), itemsize=2, prologue=True,
              res=False):
    """Whether the Pallas path can run this conv at all (the per-shape
    win/lose decision against XLA is the WINS table in
    fused_conv_bn_table.py, not this predicate). Defaults assume the bf16
    training path with a prologue — pass the real flags for exact answers."""
    return plan_blocks(x_shape, w_shape, stride, itemsize, prologue,
                       res) is not None


def _shift_masks(H, W):
    """(9, 1, HW) f32 validity masks for the 3x3 taps at pad=1. Tap t =
    (dy+1)*3 + (dx+1) reads input position (h+dy, w+dx); a flattened-HW roll
    wraps row edges, so the mask zeroes every column whose source falls
    outside the image."""
    row = np.arange(H * W) // W
    col = np.arange(H * W) % W
    masks = np.zeros((9, 1, H * W), np.float32)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            ok = ((row + dy >= 0) & (row + dy < H)
                  & (col + dx >= 0) & (col + dx < W))
            masks[(dy + 1) * 3 + (dx + 1), 0] = ok
    return masks


def _roll_cols(a, s, hw):
    """xs[:, j] = a[:, (j + s) % hw] via static slices (Mosaic-friendly)."""
    s %= hw
    if s == 0:
        return a
    return jnp.concatenate([a[:, s:], a[:, :s]], axis=1)


def _kernel(*refs, b_steps, bn, hw, taps, shifts, relu, has_prologue,
            has_res):
    import jax.experimental.pallas as pl

    it = iter(refs)
    x_ref = next(it)
    w_ref = next(it)
    mask_ref = next(it) if taps > 1 else None
    scale_ref = next(it) if has_prologue else None
    shift_ref = next(it) if has_prologue else None
    res_ref = next(it) if has_res else None
    c_ref, sum_ref, sq_ref, acc_s, acc_q = it

    b = pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        acc_q[...] = jnp.zeros_like(acc_q)

    xn = x_ref[0]  # (K, HW)
    if has_prologue:
        xn = xn * scale_ref[...] + shift_ref[...]
        if relu:
            xn = jnp.maximum(xn, jnp.zeros_like(xn))

    if taps == 1:
        c32 = jnp.dot(w_ref[...], xn, preferred_element_type=jnp.float32)
    else:
        c32 = jnp.zeros((bn, hw), jnp.float32)
        for t in range(taps):
            part = jnp.dot(w_ref[t], _roll_cols(xn, shifts[t], hw),
                           preferred_element_type=jnp.float32)
            c32 = c32 + part * mask_ref[t]
    if has_res:
        c32 = c32 + res_ref[0].astype(jnp.float32)
    c_ref[0] = c32.astype(c_ref.dtype)
    acc_s[...] += jnp.sum(c32, axis=1, keepdims=True)
    acc_q[...] += jnp.sum(c32 * c32, axis=1, keepdims=True)

    @pl.when(b == b_steps - 1)
    def _flush():
        sum_ref[...] = acc_s[...]
        sq_ref[...] = acc_q[...]


@functools.partial(jax.jit, static_argnames=("kernel_hw", "stride", "relu",
                                             "interpret"))
def _conv_block_fwd_impl(x, w, scale, shift, res, *, kernel_hw, stride,
                         relu, interpret):
    """Pallas forward. x (B,K,H,W); w (N,K,kh,kw); scale/shift (K,) or None;
    res (B,N,H',W') or None. Returns (c, ssum, ssq)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, K, H, W = x.shape
    N = w.shape[0]
    kh, kw = kernel_hw
    if (kh, kw) == (1, 1) and stride != (1, 1):
        x = x[:, :, :: stride[0], :: stride[1]]
        B, K, H, W = x.shape
    HW = H * W
    taps = kh * kw
    dt = x.dtype
    has_prologue = scale is not None
    bn = choose_blocks(B, K, N, HW, dt.itemsize, taps=taps,
                       prologue=has_prologue, res=res is not None)
    assert bn is not None, (x.shape, w.shape)  # callers gate via plan_blocks
    n_tiles = N // bn

    x3 = x.reshape(B, K, HW)
    inputs = [x3]
    in_specs = [pl.BlockSpec((1, K, HW), lambda n, b: (b, 0, 0))]
    if taps == 1:
        inputs.append(w.reshape(N, K))
        in_specs.append(pl.BlockSpec((bn, K), lambda n, b: (n, 0)))
        shifts = (0,)
    else:
        # (N,K,3,3) -> (9, N, K): tap-major so each w_ref[t] is a (bn, K)
        # stripe with K in lanes
        inputs.append(jnp.transpose(w.reshape(N, K, taps), (2, 0, 1)))
        in_specs.append(pl.BlockSpec((taps, bn, K), lambda n, b: (0, n, 0)))
        inputs.append(jnp.asarray(_shift_masks(H, W)))
        in_specs.append(pl.BlockSpec((taps, 1, HW), lambda n, b: (0, 0, 0)))
        shifts = tuple(dy * W + dx for dy in (-1, 0, 1) for dx in (-1, 0, 1))
    if has_prologue:
        inputs.append(scale.astype(dt).reshape(K, 1))
        inputs.append(shift.astype(dt).reshape(K, 1))
        in_specs.append(pl.BlockSpec((K, 1), lambda n, b: (0, 0)))
        in_specs.append(pl.BlockSpec((K, 1), lambda n, b: (0, 0)))
    if res is not None:
        inputs.append(res.reshape(B, N, HW))
        in_specs.append(pl.BlockSpec((1, bn, HW), lambda n, b: (b, n, 0)))

    params = None if interpret else pltpu.CompilerParams(
        dimension_semantics=(pltpu.GridDimensionSemantics.PARALLEL,
                             pltpu.GridDimensionSemantics.ARBITRARY))
    c, s, q = pl.pallas_call(
        functools.partial(
            _kernel, b_steps=B, bn=bn, hw=HW, taps=taps, shifts=shifts,
            relu=relu, has_prologue=has_prologue, has_res=res is not None),
        grid=(n_tiles, B),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bn, HW), lambda n, b: (b, n, 0)),
            pl.BlockSpec((bn, 1), lambda n, b: (n, 0)),
            pl.BlockSpec((bn, 1), lambda n, b: (n, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, N, HW), dt),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bn, 1), jnp.float32),
                        pltpu.VMEM((bn, 1), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(*inputs)
    return c.reshape(B, N, H, W), s[:, 0], q[:, 0]


_DNUMS = ("NCHW", "OIHW", "NCHW")


def _xla_conv(x, w, scale, shift, res, kernel_hw, stride, relu):
    """The pure-XLA reference of the fused forward (also the fallback path
    and the backward's differentiation target)."""
    if scale is not None:
        bshape = (1, -1, 1, 1)
        xn = x * scale.astype(x.dtype).reshape(bshape) \
            + shift.astype(x.dtype).reshape(bshape)
        if relu:
            xn = jnp.maximum(xn, 0)
    else:
        xn = x
    pad = (kernel_hw[0] - 1) // 2
    c = jax.lax.conv_general_dilated(
        xn, w, window_strides=stride, padding=[(pad, pad), (pad, pad)],
        dimension_numbers=_DNUMS,
        preferred_element_type=jnp.promote_types(x.dtype, jnp.float32),
    ).astype(x.dtype)
    if res is not None:
        c = c + res
    return c


def _stats_of(c):
    c32 = c.astype(jnp.float32)
    return jnp.sum(c32, axis=(0, 2, 3)), jnp.sum(c32 * c32, axis=(0, 2, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def conv_block(x, w, scale, shift, res, kernel_hw=(1, 1), stride=(1, 1),
               relu=False, use_pallas=True):
    """Fused (prologue-normalized) conv (+residual) with statistics epilogue.

    Returns ``(c, ssum, ssq)``: the conv output (x.dtype) and per-channel
    f32 sum / sum-of-squares over (B, H, W). ``scale``/``shift`` (or None)
    fold the upstream BN+ReLU into the kernel; ``res`` (or None) is added
    into the output tile before the statistics. Differentiable in x, w,
    scale, shift, res.
    """
    c, s, q = _conv_block_fwd(x, w, scale, shift, res, kernel_hw, stride,
                              relu, use_pallas)[0]
    return c, s, q


def _interpret_mode():
    return jax.default_backend() != "tpu"


def _conv_block_fwd(x, w, scale, shift, res, kernel_hw, stride, relu,
                    use_pallas):
    if use_pallas and plan_blocks(
            x.shape, w.shape, stride, itemsize=x.dtype.itemsize,
            prologue=scale is not None, res=res is not None) is not None:
        c, s, q = _conv_block_fwd_impl(
            x, w, scale, shift, res, kernel_hw=kernel_hw, stride=stride,
            relu=relu, interpret=_interpret_mode())
    else:
        c = _xla_conv(x, w, scale, shift, res, kernel_hw, stride, relu)
        s, q = _stats_of(c)
    return (c, s, q), (x, w, scale, shift, res, c)


def _conv_block_bwd(kernel_hw, stride, relu, use_pallas, saved, cts):
    x, w, scale, shift, res, c = saved
    dc, ds, dq = cts
    # fold the statistics cotangents into the output cotangent:
    # d/dc [ sum(c) . ds + sum(c^2) . dq ] = ds + 2 c dq   (per channel)
    bshape = (1, -1, 1, 1)
    dc_eff = (dc.astype(jnp.float32)
              + ds.reshape(bshape)
              + 2.0 * c.astype(jnp.float32) * dq.reshape(bshape)
              ).astype(c.dtype)

    has_prologue = scale is not None
    has_res = res is not None

    if has_prologue:
        xn = x * scale.astype(x.dtype).reshape(bshape) \
            + shift.astype(x.dtype).reshape(bshape)
        if relu:
            xn = jnp.maximum(xn, 0)
    else:
        xn = x

    pad = (kernel_hw[0] - 1) // 2

    def conv_only(xn, w):
        return jax.lax.conv_general_dilated(
            xn, w, window_strides=stride, padding=[(pad, pad), (pad, pad)],
            dimension_numbers=_DNUMS,
            preferred_element_type=jnp.promote_types(x.dtype, jnp.float32),
        ).astype(x.dtype)

    # the recomputed primal is dead code (only dc_eff uses c, and that is the
    # saved output) — XLA DCEs the duplicate convolution, keeping just the
    # transposed data/weight grads; xn's recompute is fusible elementwise.
    _, vjp_fn = jax.vjp(conv_only, xn, w)
    dxn, dw = vjp_fn(dc_eff)
    if has_prologue:
        if relu:
            dxn = dxn * (xn > 0).astype(dxn.dtype)
        dx = dxn * scale.astype(dxn.dtype).reshape(bshape)
        # per-channel reductions with explicit f32 accumulators (a bf16
        # reduce over B*H*W elements would lose the gradient's low bits)
        dxn32 = dxn.astype(jnp.float32)
        dscale = jnp.sum(dxn32 * x.astype(jnp.float32), axis=(0, 2, 3))
        dshift = jnp.sum(dxn32, axis=(0, 2, 3))
    else:
        dx, dscale, dshift = dxn, None, None
    return dx, dw, dscale, dshift, (dc_eff if has_res else None)


conv_block.defvjp(_conv_block_fwd, _conv_block_bwd)
