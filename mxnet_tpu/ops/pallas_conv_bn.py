"""Fused conv + BatchNorm Pallas TPU kernel stack (round-5 performance work).

docs/PERF.md's roofline analysis pins the ResNet-50 step at the v5e HBM
roofline (72.3 GB/step at 809 of 819 GB/s): every path to >=0.35 MFU is a
bytes-cut, and the one remaining lever is the hand-fused conv+BN kernel —
the TPU counterpart of the reference's vendor conv kernels
(/root/reference/src/operator/cudnn_convolution-inl.h) behind its published
speed table (example/image-classification/README.md:149-156).

This module is that kernel. For NCHW activations viewed as ``(B, K, H*W)``
(a free reshape — no transposes), one Pallas kernel computes

    c[b, n, hw] = sum_k w[n, k] * xn[b, k, hw]            (1x1 conv = matmul)
    c[b, n, hw] = sum_{k,t} w[t, n, k] * shift_t(xn)[b, k, hw]   (3x3, 9 taps)

with three fusions XLA cannot do (a convolution cannot be a fusion producer):

- **prologue**: ``xn = relu(x * scale + shift)`` applied in VMEM — the
  upstream BatchNorm+ReLU output is never materialized in HBM. In the
  pre-activation ResNet chain (BN -> relu -> Conv, models/resnet.py) this
  deletes one full activation write + read per edge.
- **residual epilogue**: ``c += res`` read tile-wise — the bottleneck-block
  skip add costs no separate read-read-write pass.
- **stats epilogue**: per-channel ``sum(c)`` and ``sum(c^2)`` accumulated
  from the f32 MXU accumulator across the (B,) grid sweep — the downstream
  BatchNorm's statistics pass re-reads nothing.

Layout: grid ``(N/bn, B)`` (channel stripes parallel, batch sweep carries
the stats accumulator); blocks keep the whole HW extent per instance (every
ResNet-50 @224 shape fits VMEM this way — see ``choose_blocks``). The 3x3
taps are static-slice rolls of the VMEM-resident xn tile with
host-precomputed edge masks applied to the dot *result* (a per-column mask
commutes with the contraction over K).

The autodiff boundary is exactly this kernel (``conv_block`` is a
custom_vjp). The backward has its own Pallas kernel family (the ``bwd``
argument selects it): one fused dgrad+wgrad kernel over grid ``(K/bk, B)``
that consumes the output cotangent tile-wise, folds the stats cotangents
(``dc_eff = dc + ds + 2*c*dq`` from the saved output) and the BN-prologue
backward (``relu'(xn) * scale * dxn``) in VMEM — neither the effective
cotangent nor the pre-activation gradient is ever materialized in HBM — and
accumulates ``dw[t, n, k] = sum_{b,hw} dc_eff·xn`` from the same resident
tiles in an f32 accumulator across the B sweep. Two residual policies:

- **recompute** (default): the backward re-derives ``xn = relu(x*scale +
  shift)`` from the raw input tile it streams anyway (for ``dscale``) —
  zero extra HBM traffic, a few VPU ops per element.
- **stash**: the forward emits ``xn`` as an extra output (one HBM write)
  and the backward streams it back, skipping the prologue recompute. Costs
  bytes, saves VPU — per-shape measurement (``tools/fused_stats_bench.py``)
  decides, like TVM's learned schedule tables.

``bwd="xla"`` keeps the round-5 behavior: ``jax.vjp`` of the equivalent XLA
convolution (the primal conv is dead code and DCE'd). All BN scalar math
(mean/var/normalize, moving-stat updates) stays in plain JAX in the graph
pass (executor fusion plan) so gradients flow through it naturally.
Numerics note: the kernel's statistics come from the f32 accumulator
*before* the bf16 round of c; XLA's unfused lowering reduces the rounded
activations — they differ at the bf16-epsilon level, inside BN's eps regime.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["conv_block", "supported", "plan_blocks", "choose_blocks",
           "bn_candidates", "plan_bwd_blocks", "choose_bwd_blocks"]

_VMEM_BUDGET = 12 * 1024 * 1024


def choose_blocks(B, K, N, HW, itemsize, taps=1, prologue=False, res=False,
                  emit_xn=False):
    """Pick the channel-stripe width ``bn`` (largest divisor of N, multiple
    of 8, that keeps the per-instance VMEM working set under budget) for the
    whole-HW tiling. Returns None if no stripe fits. ``emit_xn`` budgets the
    stash policy's extra xn output stream."""
    cands = bn_candidates(B, K, N, HW, itemsize, taps=taps,
                          prologue=prologue, res=res, emit_xn=emit_xn)
    return cands[0] if cands else None


def bn_candidates(B, K, N, HW, itemsize, taps=1, prologue=False, res=False,
                  emit_xn=False):
    """Every channel-stripe width that tiles within the VMEM budget,
    largest (the planner default) first — the forward kernel's bounded
    schedule space the autotuner measures (docs/PERF.md §15)."""
    out = []
    for bn in (512, 256, 128, 64, 32, 16, 8):
        if N % bn:
            continue
        est = (
            2 * K * HW * itemsize          # x tile, double-buffered
            + 2 * bn * HW * itemsize       # c tile, double-buffered
            + bn * HW * 4                  # f32 accumulator
            + taps * bn * K * itemsize     # weight stripe
            + (2 * bn * HW * itemsize if res else 0)  # residual stream, db
            + (K * HW * itemsize if (prologue or taps > 1) else 0)  # xn temp
            + (K * HW * itemsize if taps > 1 else 0)                # shifted temp
            + (taps * HW * 4 if taps > 1 else 0)                    # masks
            + (2 * K * HW * itemsize if emit_xn else 0)  # stashed xn out, db
        )
        if est <= _VMEM_BUDGET:
            out.append(bn)
    return out


def strided_dims(H, W, stride):
    """Post-stride spatial dims as the forward computes them: the kernel
    slices ``x[:, :, ::s, ::s]``, which keeps ``ceil(H/s)`` rows for odd H
    (matching XLA's pad-0 stride-s output). Every consumer of a strided
    shape — ``plan_blocks``, ``fusion.gate``, the WINS-table key — must use
    THIS arithmetic; a floor here once sent odd spatial dims near the VMEM
    budget into an in-jit assert instead of the XLA fallback."""
    return (H + stride[0] - 1) // stride[0], (W + stride[1] - 1) // stride[1]


def _conv_geometry(x_shape, w_shape, stride, itemsize):
    """Shared structural gate of the fwd and bwd planners: (B, K, N, HW,
    taps) for an eligible call, else None."""
    if len(x_shape) != 4 or len(w_shape) != 4 or itemsize > 4:
        return None
    B, K, H, W = x_shape
    N, K2, kh, kw = w_shape
    if K != K2:
        return None
    if (kh, kw) == (1, 1):
        if stride[0] != stride[1] or stride[0] not in (1, 2):
            return None
        H, W = strided_dims(H, W, stride)
        taps = 1
    elif (kh, kw) == (3, 3):
        if stride != (1, 1):
            return None
        taps = 9
    else:
        return None
    if K % 8 or H * W < 8:
        return None
    return B, K, N, H * W, taps


def plan_blocks(x_shape, w_shape, stride=(1, 1), itemsize=2, prologue=True,
                res=False, emit_xn=False):
    """The kernel's tiling decision for a concrete call: the channel-stripe
    width ``bn``, or None when this conv cannot (or should not) run on the
    Pallas path. This is the single source of truth — ``supported`` and the
    forward both call it with the SAME flags (itemsize, prologue, residual,
    xn stash), so a call that passes the gate can never hit an internal
    assert instead of the XLA fallback."""
    geo = _conv_geometry(x_shape, w_shape, stride, itemsize)
    if geo is None:
        return None
    B, K, N, HW, taps = geo
    return choose_blocks(B, K, N, HW, itemsize, taps=taps,
                         prologue=prologue, res=res, emit_xn=emit_xn)


def choose_bwd_blocks(B, K, N, HW, itemsize, taps=1, prologue=False,
                      res=False, stash=False):
    """Pick the input-channel stripe width ``bk`` for the fused backward
    (dgrad+wgrad) kernel — largest divisor of K keeping the per-instance
    VMEM working set under budget — or None when the backward cannot run on
    the Pallas path. Mirrors ``choose_blocks``' analytic estimate for the
    backward's resident set."""
    for bk in (512, 256, 128, 64, 32, 16, 8):
        if K % bk:
            continue
        est = (
            2 * 2 * N * HW * itemsize       # dc + c tiles, double-buffered
            + N * HW * (4 + itemsize)       # dc_eff f32 + rounded copy
            + taps * N * bk * itemsize      # weight stripe
            + 2 * bk * HW * itemsize        # x tile, double-buffered
            + (2 * bk * HW * itemsize if stash else 0)      # stashed xn
            + bk * HW * 4                   # da f32 accumulator
            + (bk * HW * 4 if taps > 1 else 0)              # rolled part
            + (N * HW * itemsize if taps > 1 else 0)        # masked cot.
            + (taps * HW * 4 if taps > 1 else 0)            # edge masks
            + 2 * bk * HW * itemsize        # dx tile, double-buffered
            + 2 * taps * N * bk * 4         # dw accumulator + out block
            + (2 * N * HW * itemsize if res else 0)         # dres tile, db
        )
        if est <= _VMEM_BUDGET:
            return bk
    return None


def plan_bwd_blocks(x_shape, w_shape, stride=(1, 1), itemsize=2,
                    prologue=True, res=False, stash=False):
    """Tiling decision for the fused backward kernel (the ``choose_blocks``
    counterpart of the dgrad/wgrad family): the K-stripe width ``bk``, or
    None when the backward must take the XLA fallback. Single source of
    truth for the backward gate — ``fusion.bwd_mode`` and the backward
    dispatcher both call it with the same flags."""
    geo = _conv_geometry(x_shape, w_shape, stride, itemsize)
    if geo is None:
        return None
    B, K, N, HW, taps = geo
    return choose_bwd_blocks(B, K, N, HW, itemsize, taps=taps,
                             prologue=prologue, res=res, stash=stash)


def supported(x_shape, w_shape, stride=(1, 1), itemsize=2, prologue=True,
              res=False):
    """Whether the Pallas path can run this conv at all (the per-shape
    win/lose decision against XLA is the WINS table in
    fused_conv_bn_table.py, not this predicate). Defaults assume the bf16
    training path with a prologue — pass the real flags for exact answers."""
    return plan_blocks(x_shape, w_shape, stride, itemsize, prologue,
                       res) is not None


def _shift_masks(H, W):
    """(9, 1, HW) f32 validity masks for the 3x3 taps at pad=1. Tap t =
    (dy+1)*3 + (dx+1) reads input position (h+dy, w+dx); a flattened-HW roll
    wraps row edges, so the mask zeroes every column whose source falls
    outside the image."""
    row = np.arange(H * W) // W
    col = np.arange(H * W) % W
    masks = np.zeros((9, 1, H * W), np.float32)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            ok = ((row + dy >= 0) & (row + dy < H)
                  & (col + dx >= 0) & (col + dx < W))
            masks[(dy + 1) * 3 + (dx + 1), 0] = ok
    return masks


def _roll_cols(a, s, hw):
    """xs[:, j] = a[:, (j + s) % hw] via static slices (Mosaic-friendly)."""
    s %= hw
    if s == 0:
        return a
    return jnp.concatenate([a[:, s:], a[:, :s]], axis=1)


def _kernel(*refs, b_steps, bn, hw, taps, shifts, relu, has_prologue,
            has_res, emit_xn=False, emit_stats=True):
    import jax.experimental.pallas as pl

    it = iter(refs)
    x_ref = next(it)
    w_ref = next(it)
    mask_ref = next(it) if taps > 1 else None
    scale_ref = next(it) if has_prologue else None
    shift_ref = next(it) if has_prologue else None
    res_ref = next(it) if has_res else None
    c_ref = next(it)
    sum_ref = next(it) if emit_stats else None
    sq_ref = next(it) if emit_stats else None
    xn_ref = next(it) if emit_xn else None
    acc_s, acc_q = it if emit_stats else (None, None)

    b = pl.program_id(1)

    if emit_stats:
        @pl.when(b == 0)
        def _init():
            acc_s[...] = jnp.zeros_like(acc_s)
            acc_q[...] = jnp.zeros_like(acc_q)

    xn = x_ref[0]  # (K, HW)
    if has_prologue:
        xn = xn * scale_ref[...] + shift_ref[...]
        if relu:
            xn = jnp.maximum(xn, jnp.zeros_like(xn))
    if emit_xn:
        # stash policy: the normalized activation goes to HBM for the
        # backward. The (b, 0, 0) block is revisited once per n stripe;
        # every visit writes the SAME value (xn is computed per instance
        # anyway), so the duplicate write-backs are benign.
        xn_ref[0] = xn

    if taps == 1:
        c32 = jnp.dot(w_ref[...], xn, preferred_element_type=jnp.float32)
    else:
        c32 = jnp.zeros((bn, hw), jnp.float32)
        for t in range(taps):
            part = jnp.dot(w_ref[t], _roll_cols(xn, shifts[t], hw),
                           preferred_element_type=jnp.float32)
            c32 = c32 + part * mask_ref[t]
    if has_res:
        c32 = c32 + res_ref[0].astype(jnp.float32)
    c_ref[0] = c32.astype(c_ref.dtype)
    if emit_stats:
        acc_s[...] += jnp.sum(c32, axis=1, keepdims=True)
        acc_q[...] += jnp.sum(c32 * c32, axis=1, keepdims=True)

        @pl.when(b == b_steps - 1)
        def _flush():
            sum_ref[...] = acc_s[...]
            sq_ref[...] = acc_q[...]


@functools.partial(jax.jit, static_argnames=("kernel_hw", "stride", "relu",
                                             "interpret", "emit_xn",
                                             "emit_stats", "bn_override"))
def _conv_block_fwd_impl(x, w, scale, shift, res, *, kernel_hw, stride,
                         relu, interpret, emit_xn=False, emit_stats=True,
                         bn_override=None):
    """Pallas forward. x (B,K,H,W); w (N,K,kh,kw); scale/shift (K,) or None;
    res (B,N,H',W') or None. Returns (c, ssum, ssq) plus the materialized
    prologue activation xn (post-stride shape) when ``emit_xn`` (the
    backward stash policy). ``emit_stats=False`` (grad-less inference)
    elides the statistics epilogue entirely and returns just ``c``."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    assert emit_stats or not emit_xn  # xn stash is a backward-only policy
    B, K, H, W = x.shape
    N = w.shape[0]
    kh, kw = kernel_hw
    if (kh, kw) == (1, 1) and stride != (1, 1):
        x = x[:, :, :: stride[0], :: stride[1]]
        B, K, H, W = x.shape
    HW = H * W
    taps = kh * kw
    dt = x.dtype
    has_prologue = scale is not None
    cands = bn_candidates(B, K, N, HW, dt.itemsize, taps=taps,
                          prologue=has_prologue, res=res is not None,
                          emit_xn=emit_xn)
    # the autotuner's measured stripe wins when it still tiles; anything
    # else (stale schedule, flag drift) silently demotes to the planner pick
    bn = bn_override if bn_override in cands else (
        cands[0] if cands else None)
    assert bn is not None, (x.shape, w.shape)  # callers gate via plan_blocks
    n_tiles = N // bn

    x3 = x.reshape(B, K, HW)
    inputs = [x3]
    in_specs = [pl.BlockSpec((1, K, HW), lambda n, b: (b, 0, 0))]
    if taps == 1:
        inputs.append(w.reshape(N, K))
        in_specs.append(pl.BlockSpec((bn, K), lambda n, b: (n, 0)))
        shifts = (0,)
    else:
        # (N,K,3,3) -> (9, N, K): tap-major so each w_ref[t] is a (bn, K)
        # stripe with K in lanes
        inputs.append(jnp.transpose(w.reshape(N, K, taps), (2, 0, 1)))
        in_specs.append(pl.BlockSpec((taps, bn, K), lambda n, b: (0, n, 0)))
        inputs.append(jnp.asarray(_shift_masks(H, W)))
        in_specs.append(pl.BlockSpec((taps, 1, HW), lambda n, b: (0, 0, 0)))
        shifts = tuple(dy * W + dx for dy in (-1, 0, 1) for dx in (-1, 0, 1))
    if has_prologue:
        inputs.append(scale.astype(dt).reshape(K, 1))
        inputs.append(shift.astype(dt).reshape(K, 1))
        in_specs.append(pl.BlockSpec((K, 1), lambda n, b: (0, 0)))
        in_specs.append(pl.BlockSpec((K, 1), lambda n, b: (0, 0)))
    if res is not None:
        inputs.append(res.reshape(B, N, HW))
        in_specs.append(pl.BlockSpec((1, bn, HW), lambda n, b: (b, n, 0)))

    params = None if interpret else pltpu.CompilerParams(
        dimension_semantics=(pltpu.GridDimensionSemantics.PARALLEL,
                             pltpu.GridDimensionSemantics.ARBITRARY))
    out_specs = [pl.BlockSpec((1, bn, HW), lambda n, b: (b, n, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, N, HW), dt)]
    scratch = []
    if emit_stats:
        out_specs += [pl.BlockSpec((bn, 1), lambda n, b: (n, 0)),
                      pl.BlockSpec((bn, 1), lambda n, b: (n, 0))]
        out_shape += [jax.ShapeDtypeStruct((N, 1), jnp.float32),
                      jax.ShapeDtypeStruct((N, 1), jnp.float32)]
        scratch = [pltpu.VMEM((bn, 1), jnp.float32),
                   pltpu.VMEM((bn, 1), jnp.float32)]
    if emit_xn:
        out_specs.append(pl.BlockSpec((1, K, HW), lambda n, b: (b, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B, K, HW), dt))
    outs = pl.pallas_call(
        functools.partial(
            _kernel, b_steps=B, bn=bn, hw=HW, taps=taps, shifts=shifts,
            relu=relu, has_prologue=has_prologue, has_res=res is not None,
            emit_xn=emit_xn, emit_stats=emit_stats),
        grid=(n_tiles, B),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=interpret,
    )(*inputs)
    if not emit_stats:
        return outs[0].reshape(B, N, H, W)
    c, s, q = outs[:3]
    if emit_xn:
        return (c.reshape(B, N, H, W), s[:, 0], q[:, 0],
                outs[3].reshape(B, K, H, W))
    return c.reshape(B, N, H, W), s[:, 0], q[:, 0]


_DNUMS = ("NCHW", "OIHW", "NCHW")


def _preferred(dtype):
    """preferred_element_type for the XLA conv — only when it matches the
    input dtype. Requesting f32 output from a bf16 conv makes jax.vjp's
    transpose call conv(g_f32, w_bf16), which this jax version rejects; the
    backend accumulates bf16 convs in f32 internally either way, so the
    explicit request only ever mattered for the output rounding point."""
    pet = jnp.promote_types(dtype, jnp.float32)
    return pet if pet == dtype else None


def _xla_conv(x, w, scale, shift, res, kernel_hw, stride, relu):
    """The pure-XLA reference of the fused forward (also the fallback path
    and the backward's differentiation target)."""
    if scale is not None:
        bshape = (1, -1, 1, 1)
        xn = x * scale.astype(x.dtype).reshape(bshape) \
            + shift.astype(x.dtype).reshape(bshape)
        if relu:
            xn = jnp.maximum(xn, 0)
    else:
        xn = x
    pad = (kernel_hw[0] - 1) // 2
    c = jax.lax.conv_general_dilated(
        xn, w, window_strides=stride, padding=[(pad, pad), (pad, pad)],
        dimension_numbers=_DNUMS,
        preferred_element_type=_preferred(x.dtype),
    ).astype(x.dtype)
    if res is not None:
        c = c + res
    return c


def _stats_of(c):
    c32 = c.astype(jnp.float32)
    return jnp.sum(c32, axis=(0, 2, 3)), jnp.sum(c32 * c32, axis=(0, 2, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def conv_block(x, w, scale, shift, res, kernel_hw=(1, 1), stride=(1, 1),
               relu=False, use_pallas=True, bwd="xla", bn=None):
    """Fused (prologue-normalized) conv (+residual) with statistics epilogue.

    Returns ``(c, ssum, ssq)``: the conv output (x.dtype) and per-channel
    f32 sum / sum-of-squares over (B, H, W). ``scale``/``shift`` (or None)
    fold the upstream BN+ReLU into the kernel; ``res`` (or None) is added
    into the output tile before the statistics. Differentiable in x, w,
    scale, shift, res.

    ``bwd`` selects the backward lowering: ``"xla"`` (jax.vjp of the
    unfused conv), ``"recompute"`` (fused Pallas dgrad/wgrad, prologue
    re-derived in VMEM) or ``"stash"`` (fused Pallas backward streaming the
    forward-materialized xn). Non-"xla" modes silently demote — stash →
    recompute when the forward could not emit xn, and either → "xla" when
    ``plan_bwd_blocks`` cannot tile the shape. ``bn`` overrides the forward
    channel-stripe width (the autotuner's measured schedule; an invalid
    override demotes to the planner pick).
    """
    c, s, q = _conv_block_fwd(x, w, scale, shift, res, kernel_hw, stride,
                              relu, use_pallas, bwd, bn)[0]
    return c, s, q


def conv_block_infer(x, w, scale, shift, kernel_hw=(1, 1), stride=(1, 1),
                     relu=False):
    """Grad-less inference forward: the same fused prologue+conv kernel
    with the statistics epilogue elided (at ``is_train=False`` every
    downstream BN normalizes with its moving stats, so ssum/ssq would be
    dead outputs the opaque kernel still had to compute). Returns just
    ``c``; NOT differentiable — serving/predict paths only."""
    return _conv_block_fwd_impl(x, w, scale, shift, None,
                                kernel_hw=kernel_hw, stride=stride,
                                relu=relu, interpret=_interpret_mode(),
                                emit_stats=False)


def _interpret_mode():
    return jax.default_backend() != "tpu"


def _conv_block_fwd(x, w, scale, shift, res, kernel_hw, stride, relu,
                    use_pallas, bwd="xla", bn=None):
    planned = use_pallas and plan_blocks(
        x.shape, w.shape, stride, itemsize=x.dtype.itemsize,
        prologue=scale is not None, res=res is not None) is not None
    # the stash policy is decided at FORWARD time (the extra xn output);
    # it needs the Pallas forward, a prologue to stash, a forward that
    # still fits VMEM WITH the xn output stream, and a tileable backward —
    # any miss silently demotes to recompute
    stash = (bwd == "stash" and planned and scale is not None
             and plan_blocks(
                 x.shape, w.shape, stride, itemsize=x.dtype.itemsize,
                 prologue=True, res=res is not None,
                 emit_xn=True) is not None
             and plan_bwd_blocks(
                 x.shape, w.shape, stride, itemsize=x.dtype.itemsize,
                 prologue=True, res=res is not None, stash=True) is not None)
    xn = None
    if planned:
        outs = _conv_block_fwd_impl(
            x, w, scale, shift, res, kernel_hw=kernel_hw, stride=stride,
            relu=relu, interpret=_interpret_mode(), emit_xn=stash,
            bn_override=bn)
        if stash:
            c, s, q, xn = outs
        else:
            c, s, q = outs
    else:
        c = _xla_conv(x, w, scale, shift, res, kernel_hw, stride, relu)
        s, q = _stats_of(c)
    return (c, s, q), (x, w, scale, shift, res, c, xn)


# ------------------------------------------------------------------ backward
def _bwd_kernel(*refs, b_steps, bk, hw, taps, shifts, relu, has_prologue,
                has_res, stash):
    """Fused dgrad+wgrad: one instance owns a (bk, HW) input-channel stripe
    at one batch element. The stats cotangents fold into the output
    cotangent in VMEM (dc_eff is never in HBM), dgrad contracts the weight
    stripe against it, wgrad accumulates dw from the SAME resident dc_eff
    and xn tiles across the B sweep, and the prologue backward (relu mask,
    scale, dscale/dshift reductions) runs on the f32 da before the single
    dx write."""
    import jax.experimental.pallas as pl
    from jax import lax

    it = iter(refs)
    dc_ref = next(it)                               # (1, N, HW)
    c_ref = next(it)                                # (1, N, HW)
    ds_ref = next(it)                               # (N, 1) f32
    dq_ref = next(it)                               # (N, 1) f32
    w_ref = next(it)                                # (N, bk) | (taps, N, bk)
    mask_ref = next(it) if taps > 1 else None       # (taps, 1, HW) f32
    x_ref = next(it)                                # (1, bk, HW)
    xn_ref = next(it) if stash else None            # (1, bk, HW)
    scale_ref = next(it) if has_prologue else None  # (bk, 1)
    shift_ref = next(it) if has_prologue else None  # (bk, 1)
    dx_ref = next(it)                               # (1, bk, HW)
    dw_ref = next(it)                               # (taps, N, bk) f32
    dsc_ref = next(it) if has_prologue else None    # (bk, 1) f32
    dsh_ref = next(it) if has_prologue else None    # (bk, 1) f32
    dres_ref = next(it) if has_res else None        # (1, N, HW)
    acc_w = next(it)                                # (taps, N, bk) f32
    acc_sc = next(it) if has_prologue else None     # (bk, 1) f32
    acc_sh = next(it) if has_prologue else None     # (bk, 1) f32

    b = pl.program_id(1)

    @pl.when(b == 0)
    def _init():
        acc_w[...] = jnp.zeros_like(acc_w)
        if has_prologue:
            acc_sc[...] = jnp.zeros_like(acc_sc)
            acc_sh[...] = jnp.zeros_like(acc_sh)

    dt = x_ref.dtype
    # fold the statistics cotangents into the output cotangent:
    # d/dc [ sum(c) . ds + sum(c^2) . dq ] = ds + 2 c dq   (per channel)
    dce32 = (dc_ref[0].astype(jnp.float32) + ds_ref[...]
             + 2.0 * c_ref[0].astype(jnp.float32) * dq_ref[...])
    if has_res:
        # the residual add passes the effective cotangent straight through.
        # The (b, 0, 0) block is revisited once per k stripe with identical
        # data, like the forward's stash write — benign duplicate writes.
        dres_ref[0] = dce32.astype(dt)
    # round to the activation dtype for the MXU dots, matching the XLA
    # path's bf16 cotangent
    dce = dce32.astype(dt)

    x = x_ref[0]
    if stash:
        xn = xn_ref[0]
    elif has_prologue:
        xn = x * scale_ref[...] + shift_ref[...]
        if relu:
            xn = jnp.maximum(xn, jnp.zeros_like(xn))
    else:
        xn = x

    cdims = (((0,), (0,)), ((), ()))  # (N, bk) . (N, HW) -> (bk, HW)
    wdims = (((1,), (1,)), ((), ()))  # (N, HW) . (bk, HW) -> (N, bk)
    if taps == 1:
        da = lax.dot_general(w_ref[...], dce, cdims,
                             preferred_element_type=jnp.float32)
        acc_w[0] += lax.dot_general(dce, xn, wdims,
                                    preferred_element_type=jnp.float32)
    else:
        # exact transpose of the forward's roll+mask formulation: the mask
        # rides on the (N, HW) side, the inverse roll lands the tap's
        # contribution back on its source column
        da = jnp.zeros((bk, hw), jnp.float32)
        for t in range(taps):
            m = (dce * mask_ref[t]).astype(dt)
            part = lax.dot_general(w_ref[t], m, cdims,
                                   preferred_element_type=jnp.float32)
            da = da + _roll_cols(part, -shifts[t], hw)
            acc_w[t] += lax.dot_general(m, _roll_cols(xn, shifts[t], hw),
                                        wdims,
                                        preferred_element_type=jnp.float32)

    if has_prologue:
        if relu:
            da = da * (xn > 0).astype(jnp.float32)
        dx_ref[0] = (da * scale_ref[...].astype(jnp.float32)).astype(dt)
        # per-channel reductions in the f32 accumulator (a bf16 reduce over
        # B*HW elements would lose the gradient's low bits)
        acc_sc[...] += jnp.sum(da * x.astype(jnp.float32), axis=1,
                               keepdims=True)
        acc_sh[...] += jnp.sum(da, axis=1, keepdims=True)
    else:
        dx_ref[0] = da.astype(dt)

    @pl.when(b == b_steps - 1)
    def _flush():
        dw_ref[...] = acc_w[...]
        if has_prologue:
            dsc_ref[...] = acc_sc[...]
            dsh_ref[...] = acc_sh[...]


@functools.partial(jax.jit, static_argnames=("kernel_hw", "stride", "relu",
                                             "has_res", "interpret"))
def _conv_block_bwd_impl(x, w, scale, shift, c, dc, ds, dq, xn, *,
                         kernel_hw, stride, relu, has_res, interpret):
    """Pallas fused backward. x (B,K,H,W) raw input; xn (post-stride shape)
    or None (recompute); c/dc (B,N,H',W'); ds/dq (N,) f32. Returns
    (dx, dw, dscale, dshift, dres) with dscale/dshift/dres None when the
    prologue/residual is absent."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, K, Hf, Wf = x.shape
    N = w.shape[0]
    kh, kw = kernel_hw
    strided = (kh, kw) == (1, 1) and stride != (1, 1)
    if strided:
        x = x[:, :, :: stride[0], :: stride[1]]
    B, K, H, W = x.shape
    HW = H * W
    taps = kh * kw
    dt = x.dtype
    has_prologue = scale is not None
    stash = xn is not None
    bk = choose_bwd_blocks(B, K, N, HW, dt.itemsize, taps=taps,
                           prologue=has_prologue, res=has_res, stash=stash)
    assert bk is not None, (x.shape, w.shape)  # callers gate via plan_bwd_blocks
    k_tiles = K // bk

    inputs = [dc.reshape(B, N, HW), c.reshape(B, N, HW),
              ds.reshape(N, 1), dq.reshape(N, 1)]
    in_specs = [pl.BlockSpec((1, N, HW), lambda k, b: (b, 0, 0)),
                pl.BlockSpec((1, N, HW), lambda k, b: (b, 0, 0)),
                pl.BlockSpec((N, 1), lambda k, b: (0, 0)),
                pl.BlockSpec((N, 1), lambda k, b: (0, 0))]
    if taps == 1:
        inputs.append(w.reshape(N, K))
        in_specs.append(pl.BlockSpec((N, bk), lambda k, b: (0, k)))
        shifts = (0,)
    else:
        inputs.append(jnp.transpose(w.reshape(N, K, taps), (2, 0, 1)))
        in_specs.append(pl.BlockSpec((taps, N, bk), lambda k, b: (0, 0, k)))
        inputs.append(jnp.asarray(_shift_masks(H, W)))
        in_specs.append(pl.BlockSpec((taps, 1, HW), lambda k, b: (0, 0, 0)))
        shifts = tuple(dy * W + dx for dy in (-1, 0, 1) for dx in (-1, 0, 1))
    inputs.append(x.reshape(B, K, HW))
    in_specs.append(pl.BlockSpec((1, bk, HW), lambda k, b: (b, k, 0)))
    if stash:
        inputs.append(xn.reshape(B, K, HW))
        in_specs.append(pl.BlockSpec((1, bk, HW), lambda k, b: (b, k, 0)))
    if has_prologue:
        inputs.append(scale.astype(dt).reshape(K, 1))
        inputs.append(shift.astype(dt).reshape(K, 1))
        in_specs.append(pl.BlockSpec((bk, 1), lambda k, b: (k, 0)))
        in_specs.append(pl.BlockSpec((bk, 1), lambda k, b: (k, 0)))

    out_specs = [pl.BlockSpec((1, bk, HW), lambda k, b: (b, k, 0)),
                 pl.BlockSpec((taps, N, bk), lambda k, b: (0, 0, k))]
    out_shape = [jax.ShapeDtypeStruct((B, K, HW), dt),
                 jax.ShapeDtypeStruct((taps, N, K), jnp.float32)]
    if has_prologue:
        out_specs += [pl.BlockSpec((bk, 1), lambda k, b: (k, 0)),
                      pl.BlockSpec((bk, 1), lambda k, b: (k, 0))]
        out_shape += [jax.ShapeDtypeStruct((K, 1), jnp.float32),
                      jax.ShapeDtypeStruct((K, 1), jnp.float32)]
    if has_res:
        out_specs.append(pl.BlockSpec((1, N, HW), lambda k, b: (b, 0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B, N, HW), dt))
    scratch = [pltpu.VMEM((taps, N, bk), jnp.float32)]
    if has_prologue:
        scratch += [pltpu.VMEM((bk, 1), jnp.float32),
                    pltpu.VMEM((bk, 1), jnp.float32)]

    params = None if interpret else pltpu.CompilerParams(
        dimension_semantics=(pltpu.GridDimensionSemantics.PARALLEL,
                             pltpu.GridDimensionSemantics.ARBITRARY))
    outs = pl.pallas_call(
        functools.partial(
            _bwd_kernel, b_steps=B, bk=bk, hw=HW, taps=taps, shifts=shifts,
            relu=relu, has_prologue=has_prologue, has_res=has_res,
            stash=stash),
        grid=(k_tiles, B),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=interpret,
    )(*inputs)
    it = iter(outs)
    dx = next(it).reshape(B, K, H, W)
    if strided:
        dx = jnp.zeros((B, K, Hf, Wf), dt).at[
            :, :, :: stride[0], :: stride[1]].set(dx)
    dw = next(it)  # (taps, N, K) f32
    if taps == 1:
        dw = dw[0].reshape(N, K, 1, 1)
    else:
        dw = jnp.transpose(dw, (1, 2, 0)).reshape(N, K, kh, kw)
    dw = dw.astype(w.dtype)
    dscale = next(it)[:, 0] if has_prologue else None
    dshift = next(it)[:, 0] if has_prologue else None
    dres = next(it).reshape(c.shape) if has_res else None
    return dx, dw, dscale, dshift, dres


def _conv_block_bwd(kernel_hw, stride, relu, use_pallas, bwd, bn, saved,
                    cts):
    x, w, scale, shift, res, c, xn = saved
    dc, ds, dq = cts
    has_prologue = scale is not None
    has_res = res is not None

    mode = bwd if use_pallas else "xla"
    if mode == "stash" and xn is None:
        mode = "recompute"  # forward could not emit xn (fallback/no prologue)
    if mode in ("recompute", "stash") and plan_bwd_blocks(
            x.shape, w.shape, stride, itemsize=x.dtype.itemsize,
            prologue=has_prologue, res=has_res,
            stash=(mode == "stash")) is None:
        mode = "xla"
    if mode != "xla":
        return _conv_block_bwd_impl(
            x, w, scale, shift, c, dc, ds, dq,
            xn if mode == "stash" else None,
            kernel_hw=kernel_hw, stride=stride, relu=relu, has_res=has_res,
            interpret=_interpret_mode())

    # fold the statistics cotangents into the output cotangent:
    # d/dc [ sum(c) . ds + sum(c^2) . dq ] = ds + 2 c dq   (per channel)
    bshape = (1, -1, 1, 1)
    dc_eff = (dc.astype(jnp.float32)
              + ds.reshape(bshape)
              + 2.0 * c.astype(jnp.float32) * dq.reshape(bshape)
              ).astype(c.dtype)

    if has_prologue:
        xn = x * scale.astype(x.dtype).reshape(bshape) \
            + shift.astype(x.dtype).reshape(bshape)
        if relu:
            xn = jnp.maximum(xn, 0)
    else:
        xn = x

    pad = (kernel_hw[0] - 1) // 2

    def conv_only(xn, w):
        return jax.lax.conv_general_dilated(
            xn, w, window_strides=stride, padding=[(pad, pad), (pad, pad)],
            dimension_numbers=_DNUMS,
            preferred_element_type=_preferred(x.dtype),
        ).astype(x.dtype)

    # the recomputed primal is dead code (only dc_eff uses c, and that is the
    # saved output) — XLA DCEs the duplicate convolution, keeping just the
    # transposed data/weight grads; xn's recompute is fusible elementwise.
    _, vjp_fn = jax.vjp(conv_only, xn, w)
    dxn, dw = vjp_fn(dc_eff)
    if has_prologue:
        if relu:
            dxn = dxn * (xn > 0).astype(dxn.dtype)
        dx = dxn * scale.astype(dxn.dtype).reshape(bshape)
        # per-channel reductions with explicit f32 accumulators (a bf16
        # reduce over B*H*W elements would lose the gradient's low bits)
        dxn32 = dxn.astype(jnp.float32)
        dscale = jnp.sum(dxn32 * x.astype(jnp.float32), axis=(0, 2, 3))
        dshift = jnp.sum(dxn32, axis=(0, 2, 3))
    else:
        dx, dscale, dshift = dxn, None, None
    return dx, dw, dscale, dshift, (dc_eff if has_res else None)


conv_block.defvjp(_conv_block_fwd, _conv_block_bwd)
