"""Matmul with a BatchNorm-statistics epilogue, as a Pallas TPU kernel.

docs/PERF.md's round-4 roofline analysis shows the ResNet-50 step pinned at
the HBM roofline with ~25 ms/step spent in BN-statistics reductions that
re-READ every conv output — XLA cannot fuse a reduce into a convolution
producer. For 1x1 convolutions (36 of ResNet-50's 53 convs) the conv IS a
matmul, and this kernel emits the per-column sums the statistics pass needs
*while the output tile is still in VMEM*:

    C = A @ B;   col_sum[n] = sum_m C[m, n];   col_sumsq[n] = sum_m C[m, n]^2

one HBM write for C, zero extra reads for the statistics — removing one
full activation read per fused layer versus the XLA lowering.

Grid: (N/bn, M/bm), M innermost, so each kernel instance accumulates the
column partials for its N-stripe across the M sweep in f32 VMEM scratch and
flushes them on the final M step. The statistics come from the f32 MXU
accumulator BEFORE the bf16 round of C — at least as accurate as reducing
the stored bf16 activations.

This was the round-4 measured prototype of PERF.md §4's "hand-fused
conv+BN stack". SUPERSEDED in round 5 by ``ops/pallas_conv_bn.py`` (the
NCHW-native kernel with prologue/residual/stats fusions and the fusion.py
graph pass, PERF.md §6); kept as the minimal 2-D reference kernel its
tests and the §5 loss table describe.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["matmul_with_stats", "supported"]


def supported(m, k, n, block_m=512, block_n=256, itemsize=2):
    bm, bn = min(block_m, m), min(block_n, n)
    # K is kept whole per tile: the A (bm, K) + B (K, bn) + C (bm, bn) f32
    # accumulator working set must fit VMEM (~16 MB, keep headroom for
    # double-buffering)
    vmem = (bm * k + k * bn) * itemsize + bm * bn * 4
    return (m % bm == 0 and n % bn == 0 and bm % 8 == 0 and bn % 128 == 0
            and vmem <= 12 * 1024 * 1024)


def _kernel(a_ref, b_ref, c_ref, sum_ref, sq_ref, acc_s, acc_q, *, m_tiles):
    import jax.experimental.pallas as pl

    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        acc_q[...] = jnp.zeros_like(acc_q)

    c32 = jnp.dot(a_ref[...], b_ref[...],
                  preferred_element_type=jnp.float32)
    c_ref[...] = c32.astype(c_ref.dtype)
    acc_s[...] += jnp.sum(c32, axis=0, keepdims=True)
    acc_q[...] += jnp.sum(c32 * c32, axis=0, keepdims=True)

    @pl.when(mi == m_tiles - 1)
    def _flush():
        sum_ref[...] = acc_s[...]
        sq_ref[...] = acc_q[...]


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "interpret"))
def matmul_with_stats(a, b, block_m=512, block_n=256, interpret=False):
    """``(C, col_sum, col_sumsq)`` for ``C = a @ b``.

    a: (M, K), b: (K, N); C keeps ``a.dtype``, the statistics are f32 from
    the MXU accumulator. K is kept whole per tile (1x1-conv K is at most a
    few thousand channels — comfortably VMEM-resident). Callers gating with
    ``supported()`` must pass ``itemsize=a.dtype.itemsize`` (its default, 2,
    assumes bf16) or the internal assert may still reject f32 shapes.
    """
    import jax.experimental.pallas as pl

    M, K = a.shape
    K2, N = b.shape
    assert K == K2, (a.shape, b.shape)
    bm, bn = min(block_m, M), min(block_n, N)
    assert supported(M, K, N, bm, bn, itemsize=a.dtype.itemsize), (
        a.shape, b.shape, a.dtype, bm, bn)
    m_tiles, n_tiles = M // bm, N // bn

    from jax.experimental.pallas import tpu as pltpu

    scratch = [pltpu.VMEM((1, bn), jnp.float32),
               pltpu.VMEM((1, bn), jnp.float32)]
    # N-stripes are independent (parallel); the M sweep carries the
    # statistics accumulator (arbitrary/sequential) and pipelines DMA
    params = None if interpret else pltpu.CompilerParams(
        dimension_semantics=(pltpu.GridDimensionSemantics.PARALLEL,
                             pltpu.GridDimensionSemantics.ARBITRARY))
    c, s, q = pl.pallas_call(
        functools.partial(_kernel, m_tiles=m_tiles),
        grid=(n_tiles, m_tiles),
        in_specs=[
            pl.BlockSpec((bm, K), lambda n, m: (m, 0)),
            pl.BlockSpec((K, bn), lambda n, m: (0, n)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda n, m: (m, n)),
            pl.BlockSpec((1, bn), lambda n, m: (0, n)),
            pl.BlockSpec((1, bn), lambda n, m: (0, n)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, N), a.dtype),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=interpret,
    )(a, b)
    return c, s[0], q[0]
