"""Flash attention as a differentiable Pallas TPU kernel.

The fused-softmax-attention hot path, hand-tiled for VMEM. Queries tile over
one grid axis and keys/values stream over the innermost grid axis in
``block_k`` tiles — each grid step DMAs one (block_k, D) K/V tile from HBM,
with the online-softmax running state (m, l, acc) carried across the k steps
in VMEM scratch. The (T, S) score matrix never materializes and K/V never
occupy more than one tile of VMEM, so long-S shapes stream instead of
blowing VMEM. O(T·D) memory instead of O(T·S).

Training-ready: ``jax.custom_vjp`` with recompute-style flash backward
kernels (dq and dk/dv passes re-derive the probabilities from the saved
logsumexp rather than storing P), the same structure cuDNN-era fused
attention used on GPU. This is the kernel counterpart of the reference's
cuDNN attention ops; the pure-XLA path (ops/attention.py) remains the
default, and this kernel is opted in with ``MXNET_USE_PALLAS_ATTENTION=1``
on TPU (it also runs anywhere under Pallas interpret mode, which is how the
tests exercise it on CPU).

Layout: (B, H, T, D) folded to (B*H, T, D). The causal mask is bottom-right
aligned for rectangular S >= T (decode) shapes, matching ops/attention.py;
causal with S < T is rejected by ``supported()`` (fully-masked rows would
poison the online softmax).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flash_attention", "supported", "block_schedules"]

_NEG_INF = -1e30


def block_schedules(q_shape, k_shape, causal=False):
    """Every valid (block_q, block_k) tiling for these shapes, planner
    default first — the bounded schedule space the autotuner measures
    (docs/PERF.md §15). Blocks are pre-clamped to (T, S) so each entry is
    a distinct effective tiling."""
    T, S = q_shape[2], k_shape[2]
    seen, out = set(), []
    for bq, bk in ((128, 128), (128, 256), (256, 128), (64, 128),
                   (128, 64), (64, 64), (256, 256), (32, 32)):
        eff = (min(bq, T), min(bk, S))
        if eff in seen or not supported(q_shape, k_shape, causal=causal,
                                        block_q=bq, block_k=bk):
            continue
        seen.add(eff)
        out.append(eff)
    return out


def supported(q_shape, k_shape, causal=False, block_q=128, block_k=128):
    """Whether shapes tile cleanly onto the kernel grid."""
    B, H, T, D = q_shape
    S = k_shape[2]
    if causal and S < T:
        # bottom-right alignment would fully mask rows r < T-S; the online
        # softmax has no valid key for them — use the XLA path instead
        return False
    bq, bk = min(block_q, T), min(block_k, S)
    # block dims must stay sublane-aligned (8 for f32) or Mosaic rejects them
    return (T % bq == 0 and S % bk == 0 and bq % 8 == 0 and bk % 8 == 0
            and D % 8 == 0)


def _causal_mask(s, iq, jk, block_q, block_k, offset):
    """Bottom-right-aligned causal mask for one (block_q, block_k) tile:
    query row r sees key cols <= r + (S - T)."""
    rows = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(cols <= rows + offset, s, _NEG_INF)


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, nk, offset):
    from jax.experimental import pallas as pl

    iq, jk = pl.program_id(1), pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, D)
    k = k_ref[0].astype(jnp.float32)                  # (block_k, D)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask(s, iq, jk, block_q, block_k, offset)

    m_prev, l_prev = m_scr[...], l_scr[...]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    m_scr[...] = m_new
    l_scr[...] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
    acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finish():
        l = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[...] + jnp.log(l)).astype(lse_ref.dtype)


# ------------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, scale, causal, block_q, block_k, nk, offset):
    from jax.experimental import pallas as pl

    iq, jk = pl.program_id(1), pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)                # (block_q, D)
    lse = lse_ref[0].astype(jnp.float32)              # (block_q, 1)
    delta = delta_ref[0].astype(jnp.float32)          # (block_q, 1)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask(s, iq, jk, block_q, block_k, offset)
    p = jnp.exp(s - lse)                              # recomputed probs
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq_scr[...] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(jk == nk - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k, nq, offset):
    from jax.experimental import pallas as pl

    jk, iq = pl.program_id(1), pl.program_id(2)      # q streams innermost

    @pl.when(iq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0].astype(jnp.float32)
    delta = delta_ref[0].astype(jnp.float32)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        s = _causal_mask(s, iq, jk, block_q, block_k, offset)
    p = jnp.exp(s - lse)                              # (block_q, block_k)
    dv_scr[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta)                             # (block_q, block_k)
    dk_scr[...] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        # q already carries the scale factor; dk needs none on top
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------- pallas glue
def _compiler_params(n_parallel):
    from jax.experimental.pallas import tpu as pltpu

    sem = (pltpu.GridDimensionSemantics.PARALLEL,) * n_parallel + (
        pltpu.GridDimensionSemantics.ARBITRARY,)
    return pltpu.CompilerParams(dimension_semantics=sem)


def _fwd_call(q, k, v, causal, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    S = k.shape[1]
    nq, nk = T // block_q, S // block_k
    offset = S - T
    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, nk=nk, offset=offset)
    o, lse = pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, iq, jk: (bh, jk, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, iq, jk: (bh, jk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, iq, jk: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(2),
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    S = k.shape[1]
    nq, nk = T // block_q, S // block_k
    offset = S - T
    # delta_i = sum_d dO_i O_i — cheap elementwise, fused by XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          offset=offset),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, iq, jk: (bh, jk, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, iq, jk: (bh, jk, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, iq, jk: (bh, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, iq, jk: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=None if interpret else _compiler_params(2),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          offset=offset),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, jk, iq: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, jk, iq: (bh, jk, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, jk, iq: (bh, jk, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, jk, iq: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, jk, iq: (bh, iq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, jk, iq: (bh, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda bh, jk, iq: (bh, jk, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, jk, iq: (bh, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=None if interpret else _compiler_params(2),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------------ custom vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _fwd_call(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _fwd_call(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                     interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, causal=False, scale=0.0, block_q=128,
                    block_k=128, interpret=False):
    """softmax(QKᵀ·scale)V over (B, H, T, D), streamed through VMEM.
    Differentiable (custom_vjp flash backward)."""
    B, H, T, D = q.shape
    S = k.shape[2]
    if causal and S < T:
        raise ValueError(
            "flash_attention(causal=True) requires S >= T (got T=%d, S=%d): "
            "bottom-right alignment would fully mask rows < T-S; use the "
            "XLA attention path for these shapes" % (T, S))
    if scale <= 0:
        scale = 1.0 / np.sqrt(D)
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    out = _flash(q.reshape(B * H, T, D), k.reshape(B * H, S, D),
                 v.reshape(B * H, S, D), causal, float(scale),
                 block_q, block_k, interpret)
    return out.reshape(B, H, T, D)
