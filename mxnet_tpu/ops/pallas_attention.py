"""Flash attention as a Pallas TPU kernel.

The fused-softmax-attention hot path, hand-tiled for VMEM: queries stream in
``block_q`` tiles (one per grid step), keys/values stream through an online-
softmax ``fori_loop`` in ``block_k`` tiles, so the (T, S) score matrix never
materializes in HBM — O(T·D) memory instead of O(T·S). This is the kernel
counterpart of the reference's cuDNN-fused attention-era ops; the pure-XLA
path (ops/attention.py) remains the default, and this kernel is opted in
with ``MXNET_USE_PALLAS_ATTENTION=1`` on TPU (it also runs anywhere under
Pallas interpret mode, which is how the tests exercise it on CPU).

Layout: (B, H, T, D) folded to (B*H, T, D); grid = (B*H, T/block_q); the
causal mask is bottom-right aligned for rectangular S >= T (decode) shapes,
matching ops/attention.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flash_attention", "supported"]

_NEG_INF = -1e30


def supported(q_shape, k_shape, block_q=128, block_k=128):
    """Whether shapes tile cleanly onto the kernel grid."""
    B, H, T, D = q_shape
    S = k_shape[2]
    return T % block_q == 0 and S % block_k == 0 and D % 8 == 0


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k, seq_k,
            block_q):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, D)
    nk = seq_k // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            # bottom-right aligned: query row r may see key cols <= r + (S-T)
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            offset = seq_k - pl.num_programs(1) * block_q
            s = jnp.where(cols <= rows + offset, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    D = q.shape[-1]
    init = (jnp.full((block_q, 1), _NEG_INF, jnp.float32),
            jnp.zeros((block_q, 1), jnp.float32),
            jnp.zeros((block_q, D), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, nk, body, init)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "interpret"))
def flash_attention(q, k, v, causal=False, scale=0.0, block_q=128,
                    block_k=128, interpret=False):
    """softmax(QKᵀ·scale)V over (B, H, T, D), streamed through VMEM."""
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    S = k.shape[2]
    if scale <= 0:
        scale = 1.0 / np.sqrt(D)
    block_q = min(block_q, T)
    block_k = min(block_k, S)
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)

    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_k=S, block_q=block_q),
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, 0)),
            pl.BlockSpec((1, S, D), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, iq: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, T, D)
