"""Build helper for libmxtpu_predict.so (src/predict_api.cc).

The .so embeds CPython and calls mxnet_tpu.predictor — C/C++ applications
link against it plus include/mxtpu/c_predict_api.h, the reference's
c_predict_api surface. Compiled on demand with the system toolchain and
cached under build/ like the other native components.
"""
from __future__ import annotations

import os
import sys
import sysconfig
import threading

from ._native_build import build_lib, source_path

__all__ = ["build", "lib_path"]

_SRC = source_path("predict_api.cc")
_lock = threading.Lock()


def lib_path():
    from ._native_build import _BUILD_DIR

    return os.path.join(_BUILD_DIR, "libmxtpu_predict.so")


def build(force=False):
    """Compile (if stale) and return the .so path; None if no toolchain."""
    with _lock:
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR")
        pyver = "python%d.%d" % sys.version_info[:2]
        return build_lib(_SRC, "libmxtpu_predict.so", force=force,
                         extra_flags=["-I", inc, "-L", libdir, "-l", pyver])
