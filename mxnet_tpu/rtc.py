"""Runtime kernel compilation: Pallas TPU kernels from source strings.

Counterpart of the reference's MXRtc (include/mxnet/mxrtc.h:26,
src/common/mxrtc.cc, python/mxnet/rtc.py): runtime compilation of
hand-written device kernels, CUDA-C through NVRTC there. The TPU-native
kernel language is Pallas — a python-embedded DSL lowered through Mosaic to
the TPU's VMEM/MXU/VPU — so ``Rtc`` compiles a Pallas kernel body from
source at runtime and ``push`` launches it over NDArrays. On non-TPU
backends kernels run in Pallas interpret mode (same semantics, host speed),
mirroring how the reference's rtc was CUDA-only but testable via emulation.

    kernel = mx.rtc.Rtc("scale", source='''
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0
    ''')
    y = kernel.push([x], out_shapes=[x.shape])[0]
"""
from __future__ import annotations

import textwrap

import numpy as np

from .base import MXNetError
from . import ndarray as nd

__all__ = ["Rtc"]


class Rtc:
    """Compile a Pallas kernel from source (reference: mxrtc.h MXRtc::MXRtc
    compiles CUDA source; rtc.py Rtc(name, inputs, outputs, kernel))."""

    def __init__(self, name, source, kernel_name="kernel", grid=None,
                 interpret=None):
        import jax

        self.name = name
        self._grid = grid
        if interpret is None:
            # Mosaic compilation needs a real TPU backend; interpret elsewhere
            interpret = jax.default_backend() not in ("tpu",)
        self._interpret = interpret
        namespace = {}
        try:
            code = compile(textwrap.dedent(source), "<mx.rtc:%s>" % name, "exec")
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            namespace.update({"jnp": jnp, "pl": pl, "np": np, "jax": jax})
            try:
                from jax.experimental.pallas import tpu as pltpu

                namespace["pltpu"] = pltpu
            except ImportError:
                pass
            exec(code, namespace)
        except Exception as e:
            raise MXNetError("rtc compilation of %r failed: %s" % (name, e)) from e
        if kernel_name not in namespace:
            raise MXNetError("source does not define %r" % kernel_name)
        self._kernel = namespace[kernel_name]
        self._compiled = {}

    def _build(self, out_shapes, out_dtypes):
        import jax
        from jax.experimental import pallas as pl

        key = (tuple(map(tuple, out_shapes)), tuple(out_dtypes))
        if key not in self._compiled:
            out_specs = [jax.ShapeDtypeStruct(tuple(s), d)
                         for s, d in zip(out_shapes, out_dtypes)]
            kwargs = {"interpret": self._interpret}
            if self._grid is not None:
                kwargs["grid"] = self._grid
            call = pl.pallas_call(
                self._kernel,
                out_shape=out_specs if len(out_specs) > 1 else out_specs[0],
                **kwargs,
            )
            self._compiled[key] = jax.jit(call)
        return self._compiled[key]

    def push(self, inputs, out_shapes, out_dtypes=None, grid_dims=None,
             block_dims=None):
        """Launch the kernel (reference: rtc.py Rtc.push(inputs, outputs,
        grid_dims, block_dims) — CUDA launch geometry maps to the Pallas
        ``grid`` given at construction; per-push grid/block dims are accepted
        for API parity and ignored, the Mosaic compiler owns the schedule)."""
        arrays = [x._jax() if isinstance(x, nd.NDArray) else np.asarray(x)
                  for x in inputs]
        if out_dtypes is None:
            fill = arrays[0].dtype if arrays else np.float32
            out_dtypes = [arrays[i].dtype if i < len(arrays) else fill
                          for i in range(len(out_shapes))]
        fn = self._build(out_shapes, out_dtypes)
        outs = fn(*arrays)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        return [nd.NDArray(o) for o in outs]
