"""Sharded asynchronous checkpointing (docs/FAULT_TOLERANCE.md).

The reference stack treated worker failure as a normal event (ps-lite
heartbeats behind KVStore::get_num_dead_node); what made that operable was a
checkpoint format cheap enough to write *continuously*. This module is that
format for the SPMD port, built on the PR 5 sharded-update layout
("Automatic Cross-Replica Sharding of Weight Update", PAPERS.md): under
``MXNET_KVSTORE_UPDATE=sharded`` each worker already owns exactly 1/W of
every bucket's flat optimizer state, so each worker writing *its own shard*
is the natural checkpoint — W-fold less bytes per worker, no gather, no
rank-0 bottleneck.

Layout under a checkpoint root (``MXNET_CHECKPOINT_DIR``)::

    step-00000042/
        shard-00003-of-00008.npz    # this worker's 1/W flat slices
        shard-00003-of-00008.json   # sha256 digest guard for the .npz
        ...one pair per worker...
        extra.npz                   # rank 0: aux/arg params etc. (optional)
        manifest.json               # rank 0, written LAST = commit marker

A step is **complete** iff ``manifest.json`` exists and every shard pair it
implies exists with a matching digest — completeness is judged by readers,
so no cross-worker commit barrier is needed and a crash mid-write simply
leaves an incomplete (ignored) step. The manifest is digest-guarded: it
records the bucket-plan hash, the full slot map (key sequence), step, world
size and the optimizer spec, so a loader can prove the shards mean what it
thinks they mean before touching a weight.

Writes are **asynchronous off the step path**: ``Checkpointer.save_*``
snapshots device-array *references* (jax arrays are immutable — the sharded
update replaces rather than mutates its state buffers, so a reference IS a
consistent snapshot) and hands them to a single writer thread that does the
device→host transfer and the disk I/O while training continues. Telemetry:
``checkpoint.save`` / ``checkpoint.write`` / ``checkpoint.wait`` spans, a
``checkpoint.inflight`` gauge (>0 while a write overlaps the step) and
``checkpoint.drop`` events when a newer save supersedes a queued one.

Resume paths (``docs/FAULT_TOLERANCE.md``):

* **same-W**: each worker seeds its flat shards straight from its own shard
  file (``shard_direct``) — momentum bit-parity with the run that saved.
* **different-W**: the slot map re-flattens the shard set into per-key
  optimizer states on the host (the PR 5 downgrade machinery in reverse);
  the new world's bucket engine then re-shards them under its own plan.

Every write in this module is atomic: temp file + ``os.replace``. A torn or
tampered file fails its digest/deserialization check with a structured
``MXNetError`` naming the offending path.
"""
from __future__ import annotations

import errno
import glob
import hashlib
import io as _io
import json
import logging
import os
import random
import re
import shutil
import threading
import time

import numpy as np

from .base import MXNetError
from . import faultinject as _fi
from . import telemetry as _tm

__all__ = [
    "Checkpointer", "atomic_write_bytes", "atomic_replace", "checkpoint_dir",
    "checkpoint_keep", "latest_complete", "load_manifest", "read_flat_buckets",
    "read_local_shard", "read_extra", "per_key_states", "step_dir",
    "list_steps", "read_shard_set", "read_sparse_tables",
    "sparse_shard_arrays", "sparse_manifest_section",
    "apply_retention", "prefix_retention", "load_ndarrays_checked",
    "read_sharded_pointer",
]

log = logging.getLogger("mxnet_tpu.checkpoint")

MANIFEST_NAME = "manifest.json"
FORMAT_VERSION = 1
_STEP_RE = re.compile(r"step-(\d{8,})$")


# ------------------------------------------------------------------ env knobs
def checkpoint_dir():
    """MXNET_CHECKPOINT_DIR (docs/ENV_VARS.md) — the sharded-checkpoint root;
    None when unset (sharded saves then need an explicit directory)."""
    return os.environ.get("MXNET_CHECKPOINT_DIR") or None


def checkpoint_keep():
    """MXNET_CHECKPOINT_KEEP — keep-last-K retention for checkpoint sets;
    None (default) = unlimited."""
    raw = os.environ.get("MXNET_CHECKPOINT_KEEP", "")
    if not raw:
        return None
    try:
        k = int(raw)
        if k <= 0:
            raise ValueError(k)
        return k
    except ValueError:
        log.warning("MXNET_CHECKPOINT_KEEP=%r is not a positive int; "
                    "retention disabled", raw)
        return None


def checkpoint_async():
    """MXNET_CHECKPOINT_ASYNC — `0` forces every save to block until the
    write lands (debug / NFS-without-rename semantics); default async."""
    return os.environ.get("MXNET_CHECKPOINT_ASYNC", "1").lower() not in (
        "0", "off", "false")


def checkpoint_retries():
    """MXNET_CHECKPOINT_RETRIES — how many times the writer retries a
    TRANSIENT I/O failure (EIO/ENOSPC/EAGAIN, or an injected fault at the
    ``checkpoint.write`` site) before latching it. Default 3; 0 disables."""
    raw = os.environ.get("MXNET_CHECKPOINT_RETRIES", "3")
    try:
        return max(0, int(raw))
    except ValueError:
        log.warning("MXNET_CHECKPOINT_RETRIES=%r is not an int; using 3", raw)
        return 3


_TRANSIENT_ERRNOS = (errno.EIO, errno.ENOSPC, errno.EAGAIN)


def _transient_write_error(exc):
    """Retry-worthy? Disk-level transients (EIO torn write, ENOSPC until
    retention frees space, EAGAIN) and injected faults; permission errors,
    serialization bugs etc. latch immediately."""
    if isinstance(exc, _fi.FaultInjected):
        return True
    return isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS


# -------------------------------------------------------------- atomic writes
def atomic_write_bytes(path, data: bytes):
    """Write ``data`` to ``path`` atomically (temp + os.replace): readers see
    the old file or the new file, never a torn one.

    Fault-injection site ``checkpoint.write`` (docs/RESILIENCE.md):
    ``raise``/``delay_ms``/``hang`` fire at entry; a ``torn_write`` plan
    persists only a prefix of the payload INTO THE TEMP FILE and raises
    ``OSError(EIO)`` — the crash/ENOSPC-mid-write shape. The final path is
    never torn (the replace doesn't happen), which is exactly the
    atomicity contract the injector must not be allowed to break."""
    _fi.fire("checkpoint.write")
    keep = _fi.torn_fraction("checkpoint.write")
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "wb") as f:
        f.write(data if keep is None else data[:int(len(data) * keep)])
        f.flush()
        os.fsync(f.fileno())
    if keep is not None:
        raise OSError(
            errno.EIO, "faultinject: torn write of %r (persisted %d of %d "
            "bytes into the temp file, then failed)"
            % (path, int(len(data) * keep), len(data)))
    os.replace(tmp, path)


def atomic_replace(path):
    """Context manager handing out a temp path that is os.replace'd onto
    ``path`` on clean exit and unlinked on error."""
    class _Ctx:
        def __enter__(self_):
            self_.tmp = "%s.tmp.%d" % (path, os.getpid())
            return self_.tmp

        def __exit__(self_, et, ev, tb):
            if et is None:
                os.replace(self_.tmp, path)
            else:
                try:
                    os.unlink(self_.tmp)
                except OSError:
                    pass
            return False

    return _Ctx()


def load_ndarrays_checked(path):
    """``nd.load`` with torn-file armor: any deserialization failure raises a
    structured MXNetError NAMING the offending path (a crash mid-save used
    to leave a corrupt file that failed much later with a raw struct/EOF
    error nowhere near the cause)."""
    from . import ndarray as nd

    try:
        return nd.load(path)
    except MXNetError as e:
        raise MXNetError(
            "checkpoint file %r is corrupt or not an NDArray file (%s) — "
            "likely a torn write from a crash mid-save; delete it and resume "
            "from the previous checkpoint" % (path, e)) from e
    except Exception as e:
        raise MXNetError(
            "checkpoint file %r is truncated or corrupt (%s: %s) — likely a "
            "torn write from a crash mid-save; delete it and resume from the "
            "previous checkpoint" % (path, type(e).__name__, e)) from e


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ------------------------------------------------------------------- layout
def step_dir(root, step) -> str:
    return os.path.join(root, "step-%08d" % int(step))


def _shard_base(rank, world) -> str:
    return "shard-%05d-of-%05d" % (rank, world)


def list_steps(root):
    """All step numbers present under ``root`` (complete or not), ascending."""
    steps = []
    for path in glob.glob(os.path.join(glob.escape(root), "step-*")):
        m = _STEP_RE.search(path)
        if m and os.path.isdir(path):
            steps.append(int(m.group(1)))
    return sorted(steps)


def load_manifest(root, step):
    """The manifest of one step, or None when absent/corrupt."""
    path = os.path.join(step_dir(root, step), MANIFEST_NAME)
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if m.get("format") != FORMAT_VERSION:
        log.warning("checkpoint %s has unknown format %r; ignoring",
                    path, m.get("format"))
        return None
    return m


def _step_complete(root, step, manifest) -> bool:
    d = step_dir(root, step)
    if manifest.get("kind") == "sharded":
        world = int(manifest["world"])
        for r in range(world):
            base = os.path.join(d, _shard_base(r, world))
            try:
                with open(base + ".json") as f:
                    meta = json.load(f)
                if meta.get("plan_hash") != manifest.get("plan_hash"):
                    return False
                # size check: catches a torn shard at scan time without
                # paying the full digest read (the digest still guards
                # actual loads)
                if os.path.getsize(base + ".npz") != meta.get("nbytes"):
                    return False
            except (OSError, ValueError):
                return False
    for name in manifest.get("files", ()):
        if not os.path.exists(os.path.join(d, name)):
            return False
    return True


def latest_complete(root):
    """``(step, manifest)`` of the newest COMPLETE checkpoint under ``root``,
    or None. Completeness is judged reader-side (manifest present + every
    shard it implies present with a digest sidecar matching the plan), so
    a checkpoint interrupted mid-write is simply skipped."""
    if not root or not os.path.isdir(root):
        return None
    for step in reversed(list_steps(root)):
        manifest = load_manifest(root, step)
        if manifest is not None and _step_complete(root, step, manifest):
            return step, manifest
    return None


# ---------------------------------------------------------------- retention
# An INCOMPLETE old step may be garbage from a crash — or a lagging peer's
# writer thread still flushing into it on a shared filesystem. Deleting
# under that writer fails its atomic_write_bytes and latches a spurious
# Checkpointer error on the peer, so incomplete steps only become victims
# once their directory has been quiet this long. Complete steps have every
# shard + manifest landed, so nobody is still writing them.
_INCOMPLETE_GRACE_S = 900.0


def apply_retention(root, keep, protect_step=None):
    """Delete the oldest step dirs past ``keep``, never deleting
    ``protect_step``, the newest complete step (long elastic runs must not
    grow disk without bound, but the one checkpoint recovery would reach
    for is sacred), or an incomplete step modified within the last
    ``_INCOMPLETE_GRACE_S`` seconds (a lagging worker may still be writing
    its shard into it)."""
    if keep is None:
        return []
    steps = list_steps(root)
    if len(steps) <= keep:
        return []
    newest = latest_complete(root)
    protected = {protect_step, newest[0] if newest else None}
    victims = []
    for s in steps[:-keep]:
        if s in protected:
            continue
        manifest = load_manifest(root, s)
        if manifest is None or not _step_complete(root, s, manifest):
            try:
                quiet = time.time() - os.path.getmtime(step_dir(root, s))
            except OSError:
                quiet = _INCOMPLETE_GRACE_S
            if quiet < _INCOMPLETE_GRACE_S:
                continue  # a peer's writer may still be flushing into it
        victims.append(s)
    for s in victims:
        shutil.rmtree(step_dir(root, s), ignore_errors=True)
        log.info("checkpoint retention: dropped step %d (keep=%d)", s, keep)
    return victims


def prefix_retention(prefix, keep):
    """Keep-last-K for classic ``<prefix>-NNNN.params``/``.states`` epoch
    checkpoints (callback.module_checkpoint). The newest COMPLETE epoch —
    params readable, and if its .states is a sharded pointer, the pointed-to
    shard set complete — is never deleted, even when older than the window;
    a sharded .states' backing directory is removed with its epoch."""
    if keep is None:
        return []
    epochs = []
    for path in glob.glob(glob.escape(prefix) + "-*.params"):
        m = re.search(r"-(\d{4,})\.params$", path)
        if m:
            epochs.append(int(m.group(1)))
    epochs.sort()
    if len(epochs) <= keep:
        return []

    def _complete(ep):
        params = "%s-%04d.params" % (prefix, ep)
        states = "%s-%04d.states" % (prefix, ep)
        if not os.path.exists(params):
            return False
        ptr = _read_sharded_pointer(states)
        if ptr is not None:
            got = latest_complete(ptr["dir"])
            return got is not None and got[0] == ptr["step"]
        return True

    newest_complete = next((ep for ep in reversed(epochs) if _complete(ep)),
                           None)
    victims = [ep for ep in epochs[:-keep] if ep != newest_complete]
    for ep in victims:
        for suffix in (".params", ".states"):
            path = "%s-%04d%s" % (prefix, ep, suffix)
            ptr = _read_sharded_pointer(path) if suffix == ".states" else None
            try:
                os.unlink(path)
            except OSError:
                continue
            if ptr is not None:
                shutil.rmtree(ptr["dir"], ignore_errors=True)
        log.info("checkpoint retention: dropped epoch %d of %r (keep=%d)",
                 ep, prefix, keep)
    return victims


# ------------------------------------------------------- sharded npz helpers
def _npz_bytes(arrays: dict) -> bytes:
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _load_npz_checked(path, want_digest=None):
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise MXNetError("checkpoint shard %r unreadable: %s" % (path, e)) \
            from e
    if want_digest is not None and _sha256(data) != want_digest:
        raise MXNetError(
            "checkpoint shard %r failed its digest check — the file is torn "
            "or was modified after commit; this checkpoint step is unusable"
            % path)
    try:
        with np.load(_io.BytesIO(data)) as z:
            return {k: z[k] for k in z.files}
    except Exception as e:
        raise MXNetError(
            "checkpoint shard %r is corrupt (%s: %s)"
            % (path, type(e).__name__, e)) from e


def read_local_shard(root, step, manifest, rank):
    """One worker's raw shard arrays ``{array_name: np}`` with the digest
    sidecar verified (the same-W shard-direct resume path)."""
    world = int(manifest["world"])
    base = os.path.join(step_dir(root, step), _shard_base(rank, world))
    try:
        with open(base + ".json") as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise MXNetError("checkpoint shard sidecar %r unreadable: %s"
                         % (base + ".json", e)) from e
    return _load_npz_checked(base + ".npz", meta.get("digest"))


def read_shard_set(root, step, manifest):
    """Every worker's digest-verified shard arrays, in rank order — read
    ONCE and passed to both ``read_flat_buckets`` and
    ``read_sparse_tables`` so a resume pays one disk+sha256 pass, not
    three."""
    world = int(manifest["world"])
    return [read_local_shard(root, step, manifest, r) for r in range(world)]


def read_flat_buckets(root, step, manifest, shards=None):
    """Assemble the FULL flat per-bucket arrays from every worker's shard
    file: ``{bucket_index: {"w": np, "states": [np, ...]}}``. Works for any
    saved world size — this is the re-flatten half of different-W resume."""
    n_states = int(manifest["optimizer"]["n_states"])
    if shards is None:
        shards = read_shard_set(root, step, manifest)
    out = {}
    for b in manifest["plan"]["buckets"]:
        idx = int(b["index"])
        names = ["b%d.w" % idx] + ["b%d.s%d" % (idx, i)
                                   for i in range(n_states)]
        for name in names:
            for r, sh in enumerate(shards):
                if name not in sh:
                    raise MXNetError(
                        "checkpoint step %d shard %d is missing array %r — "
                        "manifest/shard mismatch" % (step, r, name))
        w = np.concatenate([sh["b%d.w" % idx] for sh in shards])
        states = [np.concatenate([sh["b%d.s%d" % (idx, i)] for sh in shards])
                  for i in range(n_states)]
        out[idx] = {"w": w, "states": states}
    return out


# --------------------------------------------------- row-sparse table shards
# Sparse embedding tables (docs/SPARSE.md) live OUTSIDE the bucket plan —
# their optimizer state is a lazily-grown (indices, rows) set, not a flat
# slice — so they checkpoint as their own shard arrays: worker r writes the
# r-th contiguous piece of the dense table plus the r-th piece of the
# touched-index set with its state rows (``index+rows per shard``). The
# pieces are np.array_split slices of SORTED arrays, so any reader world
# re-assembles them by plain concatenation — the same any-world re-flatten
# property the flat buckets have.

def sparse_shard_arrays(sparse_tables, rank, world):
    """This worker's shard arrays for the manifest's sparse section.

    ``sparse_tables``: ``{key: {"shape", "dtype", "w" (np dense table),
    "indices" (np sorted int64), "states" ([np (nnz, ...) rows])}}``, in a
    deterministic key order (the manifest section's order names the
    ``sp<j>.*`` arrays)."""
    out = {}
    for j, key in enumerate(sorted(sparse_tables, key=str)):
        t = sparse_tables[key]
        flat = np.asarray(t["w"]).reshape(-1)
        out["sp%d.w" % j] = np.array_split(flat, world)[rank]
        out["sp%d.idx" % j] = np.array_split(
            np.asarray(t["indices"], np.int64), world)[rank]
        for i, s in enumerate(t["states"]):
            out["sp%d.s%d" % (j, i)] = np.array_split(
                np.asarray(s), world)[rank]
    return out


def sparse_manifest_section(sparse_tables):
    """The manifest rows describing the sparse shard set (order matches
    ``sparse_shard_arrays``)."""
    rows = []
    for key in sorted(sparse_tables, key=str):
        t = sparse_tables[key]
        rows.append({"key": _manifest_key(key),
                     "shape": list(t["shape"]),
                     "dtype": str(np.dtype(t["dtype"])),
                     "nnz": int(np.asarray(t["indices"]).size),
                     "n_states": len(t["states"])})
    return rows


def read_sparse_tables(root, step, manifest, shards=None):
    """Re-assemble every sparse table from the shard set:
    ``{key: {"w": np dense table, "indices": np, "states": [np rows]}}``.
    Works for ANY saved world size (concatenation of the per-rank pieces) —
    the index+rows half of the different-W re-flatten path."""
    section = manifest.get("sparse") or []
    if not section:
        return {}
    if shards is None:
        shards = read_shard_set(root, step, manifest)
    out = {}
    for j, row in enumerate(section):
        key = _manifest_key(row["key"])
        shape = tuple(row["shape"])
        names = (["sp%d.w" % j, "sp%d.idx" % j]
                 + ["sp%d.s%d" % (j, i) for i in range(row["n_states"])])
        for name in names:
            for r, sh in enumerate(shards):
                if name not in sh:
                    raise MXNetError(
                        "checkpoint step %s shard %d is missing sparse "
                        "array %r — manifest/shard mismatch"
                        % (step, r, name))
        w = np.concatenate([sh["sp%d.w" % j] for sh in shards]).reshape(shape)
        idx = np.concatenate([sh["sp%d.idx" % j] for sh in shards])
        states = [np.concatenate([sh["sp%d.s%d" % (j, i)] for sh in shards])
                  for i in range(row["n_states"])]
        if idx.size != row["nnz"]:
            raise MXNetError(
                "checkpoint step %s sparse key %r: %d touched rows in the "
                "shards, manifest says %d" % (step, key, idx.size,
                                              row["nnz"]))
        out[key] = {"w": w, "indices": idx.astype(np.int64),
                    "states": states}
    return out


def read_extra(root, step, manifest):
    """``{name: np}`` of the manifest's rank-0 extra files (aux params
    etc.; see ``Checkpointer.save_sharded(extra=)``)."""
    d = step_dir(root, step)
    out = {}
    for name in manifest.get("files", ()):
        out[name] = _load_npz_checked(os.path.join(d, name))["value"]
    return out


def _manifest_key(key):
    """JSON round-trippable key encoding (int kvstore indices stay ints)."""
    return key


def per_key_states(manifest, flats, weights=False):
    """Re-flatten: per-key full arrays from the assembled flat buckets using
    the manifest's slot map. Returns ``{key: np}`` when ``weights`` else
    ``{key: (np, ...)}`` state tuples (empty tuple for stateless
    optimizers). This is the PR 5 downgrade machinery in reverse, on the
    host — the seed for a different-W resume."""
    n_states = int(manifest["optimizer"]["n_states"])
    pending = {}
    shapes = {}
    for b in manifest["plan"]["buckets"]:
        idx = int(b["index"])
        flat = flats[idx]
        arrays = [flat["w"]] if weights else flat["states"]
        for slot in b["slots"]:
            key, offset, size, shape, dtype, src_off, part, n_parts = slot
            key = _manifest_key(key)
            shapes[key] = (tuple(shape), dtype)
            segs = [a[offset:offset + size] for a in arrays]
            pending.setdefault(key, {})[part] = segs
    out = {}
    for key, parts in pending.items():
        shape, dtype = shapes[key]
        n_arrays = 1 if weights else n_states
        full = []
        for i in range(n_arrays):
            pieces = [parts[p][i] for p in sorted(parts)]
            arr = (np.concatenate(pieces) if len(pieces) > 1
                   else pieces[0]).astype(dtype, copy=False).reshape(shape)
            full.append(arr)
        out[key] = full[0] if weights else tuple(full)
    return out


# --------------------------------------------------------------- async writer
class _WriteJob:
    __slots__ = ("fn", "step", "done", "error")

    def __init__(self, fn, step):
        self.fn = fn
        self.step = step
        self.done = threading.Event()
        self.error = None


class Checkpointer:
    """Asynchronous checkpoint writer bound to one checkpoint root.

    One daemon writer thread; at most one job queued behind the one in
    flight — a newer save supersedes a queued (not-yet-started) one, which
    is *dropped* (``checkpoint.drop``): under failure recovery only the
    newest complete checkpoint matters, so writing a stale one would waste
    the I/O budget the next one needs.
    """

    def __init__(self, directory, keep=None, async_=None):
        if not directory:
            raise MXNetError(
                "Checkpointer needs a directory (argument or "
                "MXNET_CHECKPOINT_DIR)")
        self.directory = directory
        self.keep = checkpoint_keep() if keep is None else keep
        self.async_ = checkpoint_async() if async_ is None else bool(async_)
        self._lock = _tm.named_lock("checkpoint.writer")
        self._queued = None      # superseded-able pending job
        self._active = None
        self._thread = None
        self._shutdown = False   # close() in progress; writer loop exits
        self._error = None       # first writer failure; re-raised at next op
        self._cv = _tm.named_condition("checkpoint.writer", self._lock)
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------- lifecycle
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="mxtpu-checkpoint-writer")
            self._thread.start()

    def _writer_loop(self):
        while True:
            with self._cv:
                while self._queued is None:
                    if self._shutdown:
                        return
                    self._cv.wait()
                job, self._queued = self._queued, None
                self._active = job
                self._set_inflight_locked()
            try:
                # transient I/O (EIO/ENOSPC/EAGAIN, injected faults) is
                # retried with capped jittered backoff before latching —
                # the write bodies are idempotent (temp + os.replace), so
                # a re-run never compounds a partial attempt
                retries = checkpoint_retries()
                attempt = 0
                while True:
                    try:
                        with _tm.span("checkpoint.write", step=job.step,
                                      attempt=attempt):
                            job.fn()
                        break
                    except BaseException as exc:
                        if attempt >= retries \
                                or not _transient_write_error(exc):
                            raise
                        attempt += 1
                        if _tm.enabled():
                            _tm.counter("checkpoint.retries").inc()
                        delay = min(1.0, 0.05 * (2 ** attempt)) \
                            * (0.5 + random.random())
                        log.warning(
                            "checkpoint write for step %s hit a transient "
                            "I/O error (%s); retry %d/%d in %.0fms",
                            job.step, exc, attempt, retries, delay * 1000)
                        time.sleep(delay)
            except BaseException as exc:  # latched; next save/wait raises
                log.error("checkpoint write for step %s FAILED: %s",
                          job.step, exc)
                job.error = exc
                with self._cv:
                    if self._error is None:
                        self._error = exc
            finally:
                with self._cv:
                    self._active = None
                    self._set_inflight_locked()
                    job.done.set()
                    self._cv.notify_all()

    def _set_inflight_locked(self):
        if _tm.enabled():
            _tm.gauge("checkpoint.inflight").set(
                (1 if self._active is not None else 0)
                + (1 if self._queued is not None else 0))

    def _raise_pending_error(self):
        exc, self._error = self._error, None
        if exc is not None:
            raise MXNetError("earlier async checkpoint write failed: %s"
                             % exc) from exc

    # ----------------------------------------------------------------- save
    def _submit(self, fn, step, block):
        job = _WriteJob(fn, step)
        if not self.async_:
            block = True
        with self._cv:
            self._raise_pending_error()
            if self._queued is not None:
                dropped = self._queued
                dropped.done.set()  # waiters on the stale job unblock
                if _tm.enabled():
                    _tm.counter("checkpoint.drops").inc()
                    _tm.event("checkpoint.drop", step=dropped.step,
                              superseded_by=step)
                log.info("checkpoint step %s dropped (superseded by %s "
                         "before its write started)", dropped.step, step)
            self._queued = job
            self._set_inflight_locked()
            self._cv.notify_all()
        if _tm.enabled():
            _tm.counter("checkpoint.saves").inc()
        self._ensure_thread()
        if block:
            # bounded wait (GL804 audit): a writer thread that died
            # without completing the job — hard kill, unhandled crash —
            # must surface as an error, not hang the training loop
            while not job.done.wait(5.0):
                t = self._thread
                if t is None or not t.is_alive():
                    with self._cv:
                        self._raise_pending_error()
                    raise MXNetError(
                        "checkpoint writer thread died before step %s "
                        "completed" % (job.step,))
            with self._cv:
                self._raise_pending_error()
        return job

    def wait(self):
        """Block until every outstanding write landed; re-raise a latched
        writer failure."""
        with _tm.span("checkpoint.wait"):
            with self._cv:
                while self._queued is not None or self._active is not None:
                    # bounded (GL804 audit): cv.wait releases _lock, but a
                    # dead writer would leave work queued forever
                    if not self._cv.wait(5.0):
                        t = self._thread
                        if t is None or not t.is_alive():
                            self._raise_pending_error()
                            raise MXNetError(
                                "checkpoint writer thread died with "
                                "write(s) still queued")
                self._raise_pending_error()

    def close(self):
        """Drain outstanding writes and stop the writer thread. The
        Checkpointer stays usable — a later save starts a fresh thread —
        so short-lived writers (one ``save_optimizer_states`` call) don't
        leak an idle daemon thread each. The thread stops even when the
        drain re-raises a latched write failure."""
        try:
            self.wait()
        finally:
            with self._cv:
                self._shutdown = True
                self._cv.notify_all()
            t, self._thread = self._thread, None
            if t is not None:
                t.join(timeout=10)
            with self._cv:
                self._shutdown = False

    def save_sharded(self, kv, step, extra=None, meta=None, block=False):
        """Checkpoint a sharded-update dist KVStore: this worker's 1/W flat
        shard of each bucket's weights + optimizer state, asynchronously.

        Snapshot happens NOW (device-array references + a dispatched device
        slice for the replicated weight buffer — no host transfer on the
        caller thread); the writer thread does device→host + disk. Rank 0
        additionally writes ``extra`` host arrays and the manifest (the
        commit marker). All workers must call this at the same step.
        """
        engine = getattr(kv, "_bucket_engine", None)
        sparse_tables = self._collect_sparse(kv)
        dense_ok = (engine is not None and engine.plan is not None
                    and engine.mode == "sharded" and engine._sharded_state)
        if not dense_ok and not sparse_tables:
            if engine is None or engine.plan is None:
                raise MXNetError(
                    "save_sharded needs a committed bucket plan (run at "
                    "least one push round first)")
            raise MXNetError(
                "save_sharded called while the engine is not in sharded "
                "update mode — use save_replicated (states live per key)")
        if dense_ok:
            missing = [b.index for b in engine.plan.buckets
                       if b.index not in engine._sharded_state]
            if missing:
                raise MXNetError(
                    "sharded checkpoint needs every bucket's flat state "
                    "materialized; buckets %s have not dispatched yet "
                    "(finish the push round / call finalize_all first)"
                    % missing)
            coll = engine._coll()
            rank, world = coll.rank, coll.n_workers
        else:
            rank, world = kv.rank, kv.num_workers
        opt = kv._optimizer
        kind, hyper, n_states = opt.flat_update_spec()
        with _tm.span("checkpoint.save", step=step, kind="sharded"):
            local = {}
            if dense_ok:
                for b in engine.plan.buckets:
                    sstate = engine._sharded_state[b.index]
                    shard = b.total // world
                    # device-side slice of the replicated weight buffer:
                    # async dispatch, the host transfer happens on the
                    # writer thread
                    w_loc = sstate["w_full"].addressable_data(0)
                    local["b%d.w" % b.index] = \
                        w_loc[rank * shard:(rank + 1) * shard]
                    for i, s in enumerate(sstate["states"]):
                        local["b%d.s%d" % (b.index, i)] = \
                            s.addressable_data(0)
            # row-sparse tables ride the same shard files: this worker's
            # 1/W piece of each table + touched index set + state rows
            # (host snapshots — the (indices, rows) state is host-resident
            # already, and the table slice is 1/W of the dense bytes)
            local.update(sparse_shard_arrays(sparse_tables, rank, world))
            manifest = None
            if rank == 0:
                plan_view = (engine.plan.describe_portable() if dense_ok
                             else {"buckets": []})
                manifest = {
                    "format": FORMAT_VERSION, "kind": "sharded",
                    "step": int(step), "world": world,
                    "plan_hash": engine.plan.hash if dense_ok else None,
                    "plan": plan_view,
                    "sparse": sparse_manifest_section(sparse_tables),
                    "optimizer": {
                        "kind": kind, "n_states": n_states,
                        "hyper": {k: v for k, v in hyper.items()},
                        "class": type(opt).__name__,
                    },
                    "update_counts": [[_manifest_key(k), int(v)] for k, v
                                      in opt._index_update_count.items()],
                    "num_update": int(opt.num_update),
                    "files": sorted(extra) if extra else [],
                    "meta": dict(meta or {}),
                    "written_at": time.time(),
                }
            plan_hash = engine.plan.hash if dense_ok else None
            return self._submit(
                lambda: self._write_shard(step, rank, world,
                                          plan_hash, local,
                                          extra, manifest),
                step, block)

    @staticmethod
    def _collect_sparse(kv):
        """Host-side snapshot of every row-sparse table + its lazy state
        (docs/SPARSE.md): the checkpoint view ``sparse_shard_arrays``
        slices. ``{}`` when the store has no sparse keys."""
        sp = getattr(kv, "_sparse_engine", None)
        if sp is None:
            return {}
        out = {}
        for key, (shape, dtype, st) in sp.sparse_states().items():
            out[key] = {"shape": tuple(shape), "dtype": dtype,
                        "w": np.asarray(kv._store[key]._jax()),
                        "indices": st.indices.copy(),
                        "states": [r.copy() for r in st.rows]}
        return out

    def save_replicated(self, step, weights, states_bytes=None, extra=None,
                        meta=None, world=1, rank=0, block=False):
        """Checkpoint the replicated-update (or single-process) layout: rank
        0 writes full weights (+ the per-key Updater state pickle) — every
        other rank's call is a cheap no-op so training scripts stay SPMD."""
        with _tm.span("checkpoint.save", step=step, kind="replicated"):
            if rank != 0:
                return None
            manifest = {
                "format": FORMAT_VERSION, "kind": "replicated",
                "step": int(step), "world": int(world),
                "files": (["weights.npz"]
                          + (["states.bin"] if states_bytes else [])
                          + (sorted(extra) if extra else [])),
                "meta": dict(meta or {}),
                "written_at": time.time(),
            }
            host_weights = dict(weights)
            return self._submit(
                lambda: self._write_replicated(step, host_weights,
                                               states_bytes, extra, manifest),
                step, block)

    # ---------------------------------------------------------- write bodies
    def _step_dir(self, step):
        d = step_dir(self.directory, step)
        os.makedirs(d, exist_ok=True)
        return d

    def _write_extra(self, d, extra):
        if extra:
            arrays = {k: np.asarray(v) for k, v in extra.items()}
            for name in arrays:
                atomic_write_bytes(os.path.join(d, name),
                                   _npz_bytes({"value": arrays[name]}))

    def _finish_manifest(self, d, manifest):
        if manifest is not None:
            atomic_write_bytes(os.path.join(d, MANIFEST_NAME),
                               json.dumps(manifest, indent=1).encode())
            apply_retention(self.directory, self.keep,
                            protect_step=manifest["step"])

    def _write_shard(self, step, rank, world, plan_hash, local, extra,
                     manifest):
        d = self._step_dir(step)
        host = {k: np.asarray(v) for k, v in local.items()}  # device→host
        data = _npz_bytes(host)
        base = os.path.join(d, _shard_base(rank, world))
        atomic_write_bytes(base + ".npz", data)
        atomic_write_bytes(base + ".json", json.dumps({
            "digest": _sha256(data), "rank": rank, "world": world,
            "step": int(step), "plan_hash": plan_hash,
            "nbytes": len(data)}).encode())
        if rank == 0:
            self._write_extra(d, extra)
        self._finish_manifest(d, manifest)

    def _write_replicated(self, step, weights, states_bytes, extra, manifest):
        d = self._step_dir(step)
        host = {k: np.asarray(getattr(v, "asnumpy", lambda: v)())
                for k, v in weights.items()}
        atomic_write_bytes(os.path.join(d, "weights.npz"), _npz_bytes(host))
        if states_bytes:
            atomic_write_bytes(os.path.join(d, "states.bin"), states_bytes)
        self._write_extra(d, extra)
        self._finish_manifest(d, manifest)


def _read_sharded_pointer(path):
    """Parse a sharded-optimizer-states pointer file (see
    kvstore.save_optimizer_states); None when ``path`` is absent or a
    classic pickle blob."""
    try:
        with open(path, "rb") as f:
            head = f.read(4096)
        obj = json.loads(head.decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    if isinstance(obj, dict) and obj.get("format") == "mxtpu-sharded-states":
        return obj
    return None


def read_sharded_pointer(path):
    """Public wrapper: the pointer dict ({'dir', 'step'}) or None."""
    return _read_sharded_pointer(path)
