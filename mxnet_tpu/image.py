"""Image data pipeline: decode, augment, batch.

Counterpart of the reference's image stack — the C++ record iterators
(src/io/iter_image_recordio_2.cc:559, src/io/image_aug_default.cc) and the
python ``mxnet/image.py`` iterator. TPU-native design notes: decode + augment
run on host CPU threads (a ThreadPoolExecutor per iterator — the reference's
``preprocess_threads``), producing fixed-shape NCHW float32 batches so the
device step compiles once; wrap with ``mx.io.PrefetchingIter`` (or pass
``prefetch_buffer``) to overlap host decode with device compute the way the
reference's PrefetcherIter does (src/io/iter_prefetcher.h:28).

JPEG/PNG codec: cv2 when installed, else PIL (this image ships PIL).
"""
from __future__ import annotations

import os
import random as _random
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .base import MXNetError
from . import io as _io
from . import ndarray as nd
from .recordio import MXIndexedRecordIO, MXRecordIO, unpack, _decode_img

__all__ = [
    "imdecode", "imresize", "fixed_crop", "random_crop", "center_crop",
    "color_normalize", "HorizontalFlipAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "CenterCropAug", "BrightnessJitterAug",
    "ContrastJitterAug", "SaturationJitterAug", "ColorNormalizeAug", "CastAug",
    "CreateAugmenter", "ImageIter", "ImageRecordIter", "ImageDetIter",
]


# --------------------------------------------------------------------- codec
def imdecode(buf, to_rgb=True, flag=1):
    """Decode jpeg/png bytes to an HWC uint8 array (reference: image.py
    imdecode over cv2; here cv2-or-PIL). Returns RGB by default."""
    img = _decode_img(bytes(buf), 1 if flag else 0)
    if img.ndim == 3 and to_rgb:
        img = img[:, :, ::-1]  # disk convention is BGR (cv2-compatible)
    return img


def imresize(src, w, h, interp=2):
    """Resize HWC array to (h, w) (reference: image.py resize_short/imresize)."""
    try:
        import cv2

        return cv2.resize(src, (w, h), interpolation=interp)
    except ImportError:
        from PIL import Image

        pil = Image.fromarray(np.asarray(src, np.uint8))
        return np.asarray(pil.resize((w, h), Image.BILINEAR))


def resize_short(src, size, interp=2):
    h, w = src.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h):
    return src[y0:y0 + h, x0:x0 + w]


def random_crop(src, size, rng=None):
    """(reference: image.py random_crop) size = (w, h)."""
    rng = rng or _random
    h, w = src.shape[:2]
    cw, ch = size
    if w < cw or h < ch:
        src = imresize(src, max(w, cw), max(h, ch))
        h, w = src.shape[:2]
    x0 = rng.randint(0, w - cw) if w > cw else 0
    y0 = rng.randint(0, h - ch) if h > ch else 0
    return fixed_crop(src, x0, y0, cw, ch), (x0, y0, cw, ch)


def center_crop(src, size):
    h, w = src.shape[:2]
    cw, ch = size
    if w < cw or h < ch:
        src = imresize(src, max(w, cw), max(h, ch))
        h, w = src.shape[:2]
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    return fixed_crop(src, x0, y0, cw, ch), (x0, y0, cw, ch)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32) - mean
    if std is not None:
        src /= std
    return src


# ----------------------------------------------------------------- augmenters
class Augmenter:
    """One augmentation step; called with an HWC float/uint8 array."""

    def __call__(self, src, rng):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp

    def __call__(self, src, rng):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size, self.interp = size, interp  # (w, h)

    def __call__(self, src, rng):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size

    def __call__(self, src, rng):
        return random_crop(src, self.size, rng)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        self.size = size

    def __call__(self, src, rng):
        return center_crop(src, self.size)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, rng):
        return src[:, ::-1] if rng.random() < self.p else src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        self.brightness = brightness

    def __call__(self, src, rng):
        alpha = 1.0 + rng.uniform(-self.brightness, self.brightness)
        return src.astype(np.float32) * alpha


class ContrastJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, contrast):
        self.contrast = contrast

    def __call__(self, src, rng):
        alpha = 1.0 + rng.uniform(-self.contrast, self.contrast)
        src = src.astype(np.float32)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray.mean() * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    _coef = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, saturation):
        self.saturation = saturation

    def __call__(self, src, rng):
        alpha = 1.0 + rng.uniform(-self.saturation, self.saturation)
        src = src.astype(np.float32)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1.0 - alpha)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std=None):
        self.mean = np.asarray(mean, np.float32) if mean is not None else None
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src, rng):
        src = src.astype(np.float32)
        if self.mean is not None:
            src = src - self.mean
        if self.std is not None:
            src = src / self.std
        return src


class CastAug(Augmenter):
    def __call__(self, src, rng):
        return src.astype(np.float32)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, inter_method=2):
    """Standard augmenter list (reference: image.py CreateAugmenter /
    src/io/image_aug_default.cc pipeline order: resize → crop → mirror →
    color jitter → normalize)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53], np.float32)
    if std is True:
        std = np.array([58.395, 57.12, 57.375], np.float32)
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ------------------------------------------------------------------ iterators
class _RecordSource:
    """Random-access record source over a .rec (+optional .idx) pack.

    Always offset-based (no .idx → one streaming scan collecting byte offsets,
    never payloads, so arbitrarily large packs stay out of RAM). ``get`` locks
    around the shared handle's seek+read so decode threads can fetch
    concurrently; the expensive decode/augment work stays outside the lock.
    """

    def __init__(self, path_imgrec, path_imgidx=None):
        import threading

        if path_imgidx is None and os.path.exists(
                os.path.splitext(path_imgrec)[0] + ".idx"):
            path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
        if path_imgidx:
            rec = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._offsets = [rec.idx[k] for k in rec.keys]
            self._rec = rec
        else:
            rec = MXRecordIO(path_imgrec, "r")
            self._offsets = []
            while True:
                pos = rec.tell()
                if rec.read() is None:
                    break
                self._offsets.append(pos)
            self._rec = rec
        self._lock = threading.Lock()

    def __len__(self):
        return len(self._offsets)

    def get(self, i):
        with self._lock:
            self._rec.handle.seek(self._offsets[i])
            return self._rec.read()


class ImageRecordIter(_io.DataIter):
    """Batches of decoded+augmented images from a RecordIO pack
    (reference: ImageRecordIter, src/io/iter_image_recordio_2.cc:559).

    Parameters follow the reference's ImageRecordParam/augmenter params:
    data_shape (C,H,W), shuffle, rand_crop, rand_mirror, mean_r/g/b,
    std_r/g/b, pad, num_parts/part_index (sharding), preprocess_threads,
    path_imgidx, label_width, round_batch. ``aug_list`` overrides the default
    augmenter pipeline.

    Execution: when the requested augment set is expressible natively
    (resize/crop/mirror/mean/std, RGB, single shard) the batches come from
    the C++ pipeline (src/image_native.cc — threaded libjpeg/libpng decode
    and augment off the GIL, the reference's iter_image_recordio_2.cc
    design); anything else — custom aug_list, pad, color jitter, num_parts
    sharding — runs the Python/PIL path. ``MXNET_NATIVE_IMAGE_PIPELINE=0``
    forces Python. Native batches preserve record order when unshuffled.
    ``shuffle=True`` + ``path_imgidx`` gives the Python path's full
    per-epoch permutation; shuffle WITHOUT an idx falls back to a 4096-
    record reservoir shuffle (logged) — pass the .idx for class-sorted recs.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0, pad=0, resize=0,
                 brightness=0, contrast=0, saturation=0, num_parts=1,
                 part_index=0, preprocess_threads=4, path_imgidx=None,
                 label_width=1, round_batch=True, seed=0, aug_list=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (C, H, W)")
        self.data_shape = tuple(data_shape)
        self._label_width = label_width
        self._round_batch = round_batch
        self.data_name, self.label_name = data_name, label_name
        label_shape = (batch_size,) if label_width == 1 else (batch_size, label_width)
        self.provide_data = [_io.DataDesc(data_name, (batch_size,) + self.data_shape)]
        self.provide_label = [_io.DataDesc(label_name, label_shape)]

        self._native = None
        native_ok = (aug_list is None and pad == 0 and num_parts == 1
                     and not (brightness or contrast or saturation)
                     and data_shape[0] == 3
                     # classes that know how to consume the native batches:
                     # ImageDetIter rides them bbox-aware via the pipeline's
                     # per-sample augment records (unknown subclasses fall
                     # back to the Python path)
                     and type(self) in (ImageRecordIter, ImageDetIter))
        if native_ok:
            from . import image_native

            if image_native.available():
                idx = path_imgidx if (path_imgidx and
                                      os.path.isfile(path_imgidx)) else None
                if shuffle and idx is None:
                    import logging

                    logging.warning(
                        "ImageRecordIter(native): shuffling without a "
                        "path_imgidx uses a 4096-record reservoir, not a "
                        "full permutation — pass the .idx for class-sorted "
                        "record files")
                try:
                    self._native = image_native.NativeImagePipeline(
                        path_imgrec, batch_size, self.data_shape,
                        num_workers=max(1, preprocess_threads),
                        resize=resize, rand_crop=rand_crop,
                        rand_mirror=rand_mirror,
                        mean=(mean_r, mean_g, mean_b),
                        std=(std_r, std_g, std_b),
                        label_width=getattr(self, "_native_lw", label_width),
                        shuffle_buf=4096 if shuffle else 0, seed=seed,
                        idx_path=idx if shuffle else None)
                except Exception:
                    self._native = None
        if self._native is not None:
            self._started = False  # pipeline already sits at epoch start
            return

        self._source = _RecordSource(path_imgrec, path_imgidx)
        n = len(self._source)
        self._indices = list(range(n))[part_index::num_parts]
        self._shuffle = shuffle
        self._rng = _random.Random(seed)
        self._pad = pad
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
        std = np.array([std_r, std_g, std_b], np.float32)
        self._aug = aug_list if aug_list is not None else CreateAugmenter(
            tuple(data_shape),
            resize=resize, rand_crop=rand_crop, rand_mirror=rand_mirror,
            mean=mean if mean.any() else None,
            std=std if (std != 1.0).any() else None,
            brightness=brightness, contrast=contrast, saturation=saturation)
        self._pool = (ThreadPoolExecutor(preprocess_threads)
                      if preprocess_threads > 1 else None)
        self._cursor = 0
        self.reset()

    def reset(self):
        if self._native is not None:
            if self._started:
                self._native.reset()
                self._started = False
            return
        if self._shuffle:
            self._rng.shuffle(self._indices)
        self._cursor = 0

    def _load_one(self, i, seed):
        header, payload = unpack(self._source.get(i))
        img = imdecode(payload, to_rgb=True)
        if img.ndim == 2:
            img = np.stack([img] * 3, axis=2)
        if self._pad:
            img = np.pad(img, ((self._pad, self._pad), (self._pad, self._pad),
                               (0, 0)), mode="constant")
        rng = _random.Random(seed)
        for aug in self._aug:
            img = aug(img, rng)
        chw = np.transpose(img.astype(np.float32), (2, 0, 1))
        label = np.asarray(header.label, np.float32)
        return chw, label

    def next(self):
        if self._native is not None:
            return self._next_native()
        n_left = len(self._indices) - self._cursor
        if n_left <= 0 or (not self._round_batch and n_left < self.batch_size):
            raise StopIteration
        take = min(self.batch_size, n_left)
        idxs = [self._indices[self._cursor + j] for j in range(take)]
        # pad the final short batch by cycling its own real members
        # (round_batch semantics; safe for shards smaller than the batch)
        while len(idxs) < self.batch_size:
            idxs.append(idxs[(len(idxs) - take) % take])
        seeds = [self._rng.getrandbits(32) for _ in idxs]
        if self._pool is not None:
            results = list(self._pool.map(self._load_one, idxs, seeds))
        else:
            results = [self._load_one(i, s) for i, s in zip(idxs, seeds)]
        data = np.stack([r[0] for r in results])
        labels = np.stack([self._scalar_label(r[1]) for r in results])
        self._cursor += take
        return _io.DataBatch(
            data=[nd.array(data)], label=[nd.array(labels)],
            pad=self.batch_size - take,
            provide_data=self.provide_data, provide_label=self.provide_label)

    def _next_native(self):
        self._started = True
        data, labels, n = self._native.next_batch()
        if n == 0 or (not self._round_batch and n < self.batch_size):
            raise StopIteration
        data = data.copy()  # the pipeline reuses its staging buffers
        labels = labels.copy()
        if n < self.batch_size:
            # round_batch: pad the tail by cycling its own real members
            for j in range(n, self.batch_size):
                data[j] = data[j % n]
                labels[j] = labels[j % n]
        lab = labels[:, 0] if self._label_width == 1 else labels
        return _io.DataBatch(
            data=[nd.array(data)], label=[nd.array(lab)],
            pad=self.batch_size - n,
            provide_data=self.provide_data, provide_label=self.provide_label)

    def _scalar_label(self, label):
        arr = np.atleast_1d(label)
        if self._label_width == 1:
            return np.float32(arr.flat[0])
        return arr[: self._label_width].astype(np.float32)


# reference alias: raw uint8 variant (same pipeline; cast happens in augs)
ImageRecordUInt8Iter = ImageRecordIter


class ImageDetIter(ImageRecordIter):
    """Detection variant (reference: ImageDetRecordIter,
    src/io/iter_image_det_recordio.cc:563): labels are variable-length
    ``[cls, xmin, ymin, xmax, ymax]`` rows (coords normalized to the
    original image), padded with -1 to ``(batch, max_objects, 5)``.

    Rides the native C++ decode/augment pipeline bbox-aware (reference:
    src/io/image_det_aug_default.cc did the box math in C++): pixels are
    cropped/mirrored natively and the boxes are transformed here from each
    sample's augment record {pre-crop W/H, crop origin, mirror} — an
    aspect-preserving resize leaves normalized coords unchanged, so crop
    geometry + mirror is the whole transform. Boxes are clipped to the crop
    and dropped when degenerate. The Python fallback path (custom aug_list,
    pad, jitter...) does NOT adjust boxes for crop/mirror — it warns when
    those augments are requested."""

    def __init__(self, *args, max_objects=8, **kwargs):
        self._max_objects = max_objects
        # native label copy: room for max_objects rows (extra rows are
        # truncated, matching _scalar_label)
        self._native_lw = max_objects * 5
        kwargs.setdefault("label_name", "label")
        super().__init__(*args, **kwargs)
        self.provide_label = [_io.DataDesc(
            self.label_name, (self.batch_size, max_objects, 5))]
        if self._native is None and (kwargs.get("rand_crop")
                                     or kwargs.get("rand_mirror")):
            import logging

            logging.warning(
                "ImageDetIter: Python fallback path does not adjust bboxes "
                "for rand_crop/rand_mirror — use the native pipeline "
                "(default augments, MXNET_NATIVE_IMAGE_PIPELINE=1) for "
                "geometry-consistent detection labels")

    def _scalar_label(self, label):
        rows = np.asarray(label, np.float32).reshape(-1, 5)
        out = -np.ones((self._max_objects, 5), np.float32)
        out[: min(len(rows), self._max_objects)] = rows[: self._max_objects]
        return out

    def _next_native(self):
        self._started = True
        data, labels, aug, n = self._native.next_batch(with_aug=True)
        if n == 0 or (not self._round_batch and n < self.batch_size):
            raise StopIteration
        data = data.copy()  # the pipeline reuses its staging buffers
        out_h, out_w = self.data_shape[1], self.data_shape[2]
        lab = -np.ones((self.batch_size, self._max_objects, 5), np.float32)
        for j in range(n):
            length = int(aug[j, 5])
            rows = labels[j, : length - (length % 5)].reshape(-1, 5).copy()
            W, H, x0, y0, mirror = aug[j, :5]
            identity = (x0 == 0 and y0 == 0 and mirror == 0
                        and W == out_w and H == out_h)
            if len(rows) and not identity:
                rows[:, 1] = (rows[:, 1] * W - x0) / out_w
                rows[:, 3] = (rows[:, 3] * W - x0) / out_w
                rows[:, 2] = (rows[:, 2] * H - y0) / out_h
                rows[:, 4] = (rows[:, 4] * H - y0) / out_h
                if mirror:
                    rows[:, 1], rows[:, 3] = 1.0 - rows[:, 3], 1.0 - rows[:, 1]
                # clip to the crop, drop boxes the crop removed — ONLY when
                # geometry changed (an un-augmented record's rows pass
                # through verbatim, matching the Python path exactly)
                np.clip(rows[:, 1:], 0.0, 1.0, out=rows[:, 1:])
                keep = ((rows[:, 3] - rows[:, 1] > 1e-4)
                        & (rows[:, 4] - rows[:, 2] > 1e-4))
                rows = rows[keep]
            rows = rows[: self._max_objects]
            lab[j, : len(rows)] = rows
        for j in range(n, self.batch_size):  # round_batch tail pad
            data[j] = data[j % n]
            lab[j] = lab[j % n]
        return _io.DataBatch(
            data=[nd.array(data)], label=[nd.array(lab)],
            pad=self.batch_size - n,
            provide_data=self.provide_data, provide_label=self.provide_label)


class ImageIter(_io.DataIter):
    """Python-level image iterator over a .lst + image root (reference:
    python/mxnet/image.py ImageIter). For .rec input use ImageRecordIter."""

    def __init__(self, batch_size, data_shape, path_imglist=None,
                 path_root=".", shuffle=False, aug_list=None, seed=0,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        if path_imglist is None:
            raise MXNetError("ImageIter needs path_imglist (or use ImageRecordIter)")
        self._items = []
        with open(path_imglist) as f:
            for line in f:
                parts = line.strip().split("\t")
                if len(parts) >= 3:
                    self._items.append((float(parts[1]),
                                        os.path.join(path_root, parts[-1])))
        self._shuffle = shuffle
        self._rng = _random.Random(seed)
        self.data_shape = tuple(data_shape)
        self._aug = aug_list if aug_list is not None else CreateAugmenter(data_shape)
        self._cursor = 0
        self.provide_data = [_io.DataDesc(data_name, (batch_size,) + self.data_shape)]
        self.provide_label = [_io.DataDesc(label_name, (batch_size,))]
        self.reset()

    def reset(self):
        if self._shuffle:
            self._rng.shuffle(self._items)
        self._cursor = 0

    def next(self):
        if self._cursor + self.batch_size > len(self._items):
            raise StopIteration
        data, labels = [], []
        for j in range(self.batch_size):
            label, path = self._items[self._cursor + j]
            with open(path, "rb") as f:
                img = imdecode(f.read())
            if img.ndim == 2:
                img = np.stack([img] * 3, axis=2)
            for aug in self._aug:
                img = aug(img, self._rng)
            data.append(np.transpose(img.astype(np.float32), (2, 0, 1)))
            labels.append(label)
        self._cursor += self.batch_size
        return _io.DataBatch(data=[nd.array(np.stack(data))],
                             label=[nd.array(np.asarray(labels, np.float32))],
                             pad=0, provide_data=self.provide_data,
                             provide_label=self.provide_label)
