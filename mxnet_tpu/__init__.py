"""mxnet_tpu: a TPU-native deep learning framework.

A brand-new framework with the capabilities of pre-Gluon MXNet 0.9 (the
reference described in SURVEY.md), designed TPU-first on JAX/XLA: imperative
NDArray + symbolic Symbol/Executor over one operator registry, a Module
training layer, KVStore-style data parallelism lowered to XLA collectives over
a device mesh, and lax.scan RNNs. Importable as ``mx`` for script parity:

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
__version__ = "0.1.0"

import os as _os

if _os.environ.get("MXNET_DEFAULT_CONTEXT", "").startswith("cpu"):
    # Force the CPU backend before any jax backend initializes. The env var
    # JAX_PLATFORMS alone is not enough on images whose sitecustomize imports
    # jax with an accelerator platform preset — the config route always works
    # as long as no computation ran yet (same trick as tests/conftest.py).
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - jax absent or backend already up
        pass

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from .attribute import AttrScope
from . import name
from . import ndarray
from . import ndarray as nd
from . import random
from . import random as rnd
from . import ops

__all__ = [
    "MXNetError",
    "Context",
    "AttrScope",
    "cpu",
    "gpu",
    "tpu",
    "current_context",
    "name",
    "nd",
    "ndarray",
    "random",
    "ops",
]


def __getattr__(name):
    # lazy subsystem imports keep `import mxnet_tpu` light and avoid cycles
    import importlib

    lazy = {
        "analysis": ".analysis",
        "sym": ".symbol",
        "symbol": ".symbol",
        "executor": ".executor",
        "mod": ".module",
        "module": ".module",
        "io": ".io",
        "optimizer": ".optimizer",
        "lr_scheduler": ".lr_scheduler",
        "metric": ".metric",
        "initializer": ".initializer",
        "init": ".initializer",
        "kvstore": ".kvstore",
        "kv": ".kvstore",
        "dist": ".dist",
        "engine": ".engine",
        "predictor": ".predictor",
        "rtc": ".rtc",
        "callback": ".callback",
        "monitor": ".monitor",
        "mon": ".monitor",
        "rnn": ".rnn",
        "model": ".model",
        "autograd": ".autograd",
        "operator": ".operator",
        "parallel": ".parallel",
        "test_utils": ".test_utils",
        "visualization": ".visualization",
        "viz": ".visualization",
        "profiler": ".profiler",
        "telemetry": ".telemetry",
        "faultinject": ".faultinject",
        "serving": ".serving",
        "sparse": ".sparse",
        "checkpoint": ".checkpoint",
        "recordio": ".recordio",
        "image": ".image",
        "img": ".image",
        "models": ".models",
    }
    if name in lazy:
        return importlib.import_module(lazy[name], __name__)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
