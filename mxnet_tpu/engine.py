"""Execution engine facade: var-dependency scheduling for host-side work.

Counterpart of the reference's engine layer (include/mxnet/engine.h:75-229 —
``NewVariable``/``Push``/``WaitForVar``/``WaitForAll`` — with the
ThreadedEnginePerDevice / ThreadedEngine / NaiveEngine policies selected by
``MXNET_ENGINE_TYPE``, src/engine/engine.cc:13-39). The TPU division of
labor: XLA/PJRT async dispatch already does the reference engine's *device*
job (stream ordering, overlap, data-dependency sequencing), so this engine
schedules host-side stages — IO decode, checkpoint writes, callbacks — and
provides the reference's synchronization facade and the NaiveEngine-style
synchronous debug mode (SURVEY.md §5.2: ``MXNET_ENGINE_TYPE=NaiveEngine``
serializes everything for debugging).

Backends:
  * ``ThreadedEngine`` / ``ThreadedEnginePerDevice`` — the native C++
    scheduler (src/engine_native.cc) via ctypes; pure-python thread pool
    fallback when no compiler exists.
  * ``NaiveEngine`` — run-on-push, single-threaded, deterministic.

Example::

    eng = mx.engine.get()
    v = eng.new_variable()
    eng.push(load_shard, const_vars=[], mutable_vars=[v])
    eng.push(lambda: consume(), const_vars=[v], mutable_vars=[])
    eng.wait_for_var(v)
"""
from __future__ import annotations

import ctypes
import os
import threading

from .base import MXNetError
from . import telemetry as _tm

__all__ = ["Engine", "NaiveEngine", "ThreadedEngine", "get", "set_engine_type"]


def _traced_op(fn, backend):
    """Wrap a pushed op so its execution shows up as an ``engine.op`` span
    (the reference profiler's per-op start/end stamps, profiler.cc). Only
    called when telemetry tracing is on — the off path pushes ``fn``
    untouched."""
    name = getattr(fn, "__name__", "op")

    def run():
        with _tm.span("engine.op", op=name, backend=backend):
            fn()

    return run

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "src", "engine_native.cc")

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        from ._native_build import build_lib

        path = build_lib(_SRC, "libmxtpu_engine.so")
        if path is None:
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except Exception:
            _lib_failed = True
            return None
        lib.mxeng_create.restype = ctypes.c_void_p
        lib.mxeng_create.argtypes = [ctypes.c_int]
        lib.mxeng_new_var.restype = ctypes.c_int64
        lib.mxeng_new_var.argtypes = [ctypes.c_void_p]
        lib.mxeng_push.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.mxeng_wait_for_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.mxeng_wait_for_all.argtypes = [ctypes.c_void_p]
        lib.mxeng_pending.restype = ctypes.c_int64
        lib.mxeng_pending.argtypes = [ctypes.c_void_p]
        lib.mxeng_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


_OPFN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def _unknown_var_error(var):
    """``wait_for_var`` on a var this engine never issued nor saw in a push
    used to be undefined behavior (return-immediately at best, a native wait
    on a phantom id at worst — found while speccing the race detector,
    analysis/engine_race.py GL102). Make it a loud, clear error."""
    return MXNetError(
        "wait_for_var: unknown engine variable %r — never created by "
        "new_variable() nor used by any push on this engine, so waiting on "
        "it is undefined. Note: vars do not survive set_engine_type(); this "
        "check is best-effort and a stale id can still alias a var the new "
        "engine issued, so callers holding vars across a swap must compare "
        "engine identity themselves (as model.py's checkpoint vars do)."
        % (var,))


class Engine:
    """Engine interface (reference: include/mxnet/engine.h Engine)."""

    def new_variable(self):
        raise NotImplementedError

    def push(self, fn, const_vars=(), mutable_vars=()):
        """Schedule ``fn()`` to run once all pending writes of ``const_vars``
        and all pending ops of ``mutable_vars`` drain."""
        raise NotImplementedError

    def wait_for_var(self, var):
        """Block until every pending op touching ``var`` drains. Raises
        ``MXNetError`` if ``var`` was never created by (or pushed through)
        this engine."""
        raise NotImplementedError

    def wait_for_all(self):
        raise NotImplementedError


class NaiveEngine(Engine):
    """Synchronous run-on-push engine (reference: src/engine/naive_engine.cc;
    the §5.2 debug mode — deterministic, single-threaded, gdb-able)."""

    def __init__(self):
        self._next = 1
        # FOREIGN var ids only (not issued by new_variable) — issued ids are
        # covered by the 1.._next watermark, so this set stays empty in
        # normal use and never grows per batch
        self._pushed = set()

    def new_variable(self):
        v = self._next
        self._next += 1
        return v

    def push(self, fn, const_vars=(), mutable_vars=()):
        if _tm.enabled():
            _tm.counter("engine.push").inc()
            if _tm.tracing():
                fn = _traced_op(fn, "naive")
        for v in (*const_vars, *mutable_vars):
            if not (isinstance(v, int) and 1 <= v < self._next):
                self._pushed.add(v)
        fn()

    def wait_for_var(self, var):
        if not (isinstance(var, int) and 1 <= var < self._next) \
                and var not in self._pushed:
            raise _unknown_var_error(var)
        _tm.event("engine.wait_for_var", backend="naive")

    def wait_for_all(self):
        _tm.event("engine.wait_for_all", backend="naive")


class ThreadedEngine(Engine):
    """Native C++ threaded var-dependency scheduler (src/engine_native.cc),
    python-threads fallback (reference: threaded_engine_perdevice.cc;
    ``MXNET_CPU_WORKER_NTHREADS`` controls pool size)."""

    def __init__(self, num_workers=None):
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "4"))
        self._num_workers = num_workers
        self._lib = _load_lib()
        self._keep = {}  # op id -> ctypes thunk keepalive
        self._keep_lock = threading.Lock()
        self._next_op = 1
        self._errors = []
        self._done = []  # completed op ids whose thunks can be purged
        # native ids are sequential from 1 (src/engine_native.cc next_var_),
        # so issued vars are covered by a watermark; only FOREIGN ids seen in
        # pushes need a set — empty in normal use, never grows per batch
        self._max_issued = 0
        self._foreign_vars = set()
        if self._lib is not None:
            self._handle = ctypes.c_void_p(self._lib.mxeng_create(num_workers))
        else:
            self._py = _PythonThreadedEngine(num_workers)

    @property
    def native(self) -> bool:
        return self._lib is not None

    def new_variable(self):
        if self._lib is None:
            return self._py.new_variable()
        v = self._lib.mxeng_new_var(self._handle)
        if v > self._max_issued:
            self._max_issued = v
        return v

    def push(self, fn, const_vars=(), mutable_vars=()):
        if _tm.enabled():
            _tm.counter("engine.push").inc()
            if _tm.tracing():
                fn = _traced_op(fn, "native" if self._lib is not None
                                else "python")
        if self._lib is None:
            return self._py.push(fn, const_vars, mutable_vars)
        for v in (*const_vars, *mutable_vars):
            if not (isinstance(v, int) and 1 <= v <= self._max_issued):
                self._foreign_vars.add(v)
        with self._keep_lock:
            op_id = self._next_op
            self._next_op += 1

        def trampoline(_):
            try:
                fn()
            except BaseException as e:  # surfaced on wait_for_all
                self._errors.append(e)
            finally:
                self._done.append(op_id)  # purged later, NOT freed mid-call

        cb = _OPFN(trampoline)
        with self._keep_lock:
            self._keep[op_id] = cb  # keep the ctypes thunk alive until done
            # NOTE: thunks are purged only in wait_for_all — an id lands in
            # _done before its native closure frame fully unwinds, so purging
            # here could free a closure a preempted worker thread is still
            # returning through
        carr = (ctypes.c_int64 * len(const_vars))(*const_vars)
        marr = (ctypes.c_int64 * len(mutable_vars))(*mutable_vars)
        self._lib.mxeng_push(self._handle, ctypes.cast(cb, ctypes.c_void_p),
                             None, carr, len(const_vars), marr, len(mutable_vars))

    def wait_for_var(self, var):
        if self._lib is None:
            return self._py.wait_for_var(var)
        if not (isinstance(var, int) and 1 <= var <= self._max_issued) \
                and var not in self._foreign_vars:
            # the native GetVar would silently conjure a fresh idle Var for
            # any int64 — return-immediately on a typo'd id. Fail loudly.
            raise _unknown_var_error(var)
        with _tm.span("engine.wait_for_var", backend="native"):
            self._lib.mxeng_wait_for_var(self._handle, var)
        self._raise_pending()

    def wait_for_all(self):
        if self._lib is None:
            return self._py.wait_for_all()
        with _tm.span("engine.wait_for_all", backend="native"):
            self._lib.mxeng_wait_for_all(self._handle)
        with self._keep_lock:
            # every op drained and its callback fully returned — purge all
            while self._done:
                self._keep.pop(self._done.pop(0), None)
        self._raise_pending()

    def _raise_pending(self):
        if self._errors:
            err = self._errors[:]
            del self._errors[:]
            raise MXNetError("engine op failed: %r" % (err[0],)) from err[0]

    def __del__(self):
        try:
            if self._lib is not None and self._handle:
                self._lib.mxeng_wait_for_all(self._handle)
                self._lib.mxeng_destroy(self._handle)
                self._handle = None
        except Exception:
            pass


class _PythonThreadedEngine(Engine):
    """GIL-bound fallback with identical semantics (used when g++ is absent)."""

    def __init__(self, num_workers):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(num_workers)
        self._cond = threading.Condition()
        self._var_queues = {}  # var -> list of (op_id, is_write)
        self._running = {}     # var -> [readers, writer_flag]
        self._pending = 0
        self._next = 1
        self._ops = {}         # op_id -> (fn, const, mut)
        self._errors = []

    def new_variable(self):
        with self._cond:
            v = self._next
            self._next += 1
            self._var_queues[v] = []
            self._running[v] = [0, False]
            return v

    def push(self, fn, const_vars=(), mutable_vars=()):
        mutable_vars = list(dict.fromkeys(mutable_vars))
        const_vars = [v for v in dict.fromkeys(const_vars) if v not in mutable_vars]
        with self._cond:
            op_id = self._next
            self._next += 1
            self._ops[op_id] = (fn, const_vars, mutable_vars)
            self._pending += 1
            for v in const_vars:
                self._var_queues.setdefault(v, []).append((op_id, False))
            for v in mutable_vars:
                self._var_queues.setdefault(v, []).append((op_id, True))
            self._try_claim(op_id)

    def _eligible(self, vid, op_id, is_write):
        readers, writer = self._running.setdefault(vid, [0, False])
        if writer:
            return False
        if is_write and readers > 0:
            return False
        for qid, qwrite in self._var_queues.setdefault(vid, []):
            if qid == op_id:
                return True
            if is_write or qwrite:
                return False
        return False

    def _try_claim(self, op_id):
        fn, const_vars, mutable_vars = self._ops[op_id]
        for v in const_vars:
            if not self._eligible(v, op_id, False):
                return
        for v in mutable_vars:
            if not self._eligible(v, op_id, True):
                return
        for v in const_vars:
            self._running[v][0] += 1
            self._var_queues[v].remove((op_id, False))
        for v in mutable_vars:
            self._running[v][1] = True
            self._var_queues[v].remove((op_id, True))
        self._pool.submit(self._run, op_id)

    def _run(self, op_id):
        fn, const_vars, mutable_vars = self._ops[op_id]
        try:
            fn()
        except BaseException as e:
            with self._cond:
                self._errors.append(e)
        with self._cond:
            for v in const_vars:
                self._running[v][0] -= 1
            for v in mutable_vars:
                self._running[v][1] = False
            del self._ops[op_id]
            self._pending -= 1
            for v in const_vars + mutable_vars:
                for qid, qwrite in list(self._var_queues.get(v, [])):
                    self._try_claim(qid)
                    if qwrite:
                        break
            self._cond.notify_all()

    def wait_for_var(self, var):
        with _tm.span("engine.wait_for_var", backend="python"), self._cond:
            if var not in self._var_queues:
                # neither new_variable() nor any push registered this id —
                # the old behavior (return immediately) silently "succeeded"
                # on typo'd/stale vars
                raise _unknown_var_error(var)
            self._cond.wait_for(
                lambda: not self._var_queues.get(var)
                and self._running.get(var, [0, False]) == [0, False])
            self._raise_pending()

    def wait_for_all(self):
        with _tm.span("engine.wait_for_all", backend="python"), self._cond:
            self._cond.wait_for(lambda: self._pending == 0)
            self._raise_pending()

    def _raise_pending(self):
        if self._errors:
            err = self._errors[:]
            del self._errors[:]
            raise MXNetError("engine op failed: %r" % (err[0],)) from err[0]


_engine = None
_engine_lock = threading.Lock()


def get() -> Engine:
    """The process engine, selected by ``MXNET_ENGINE_TYPE`` (reference:
    src/engine/engine.cc CreateEngine; default ThreadedEnginePerDevice)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = _create(os.environ.get("MXNET_ENGINE_TYPE",
                                             "ThreadedEnginePerDevice"))
        return _engine


def set_engine_type(name: str) -> Engine:
    """Swap the process engine (waits for the old one to drain)."""
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.wait_for_all()
        _engine = _create(name)
        return _engine


def _create(name: str) -> Engine:
    if name == "NaiveEngine":
        return NaiveEngine()
    if name in ("ThreadedEngine", "ThreadedEnginePerDevice"):
        return ThreadedEngine()
    raise MXNetError("unknown MXNET_ENGINE_TYPE %r" % name)
