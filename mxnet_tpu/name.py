"""Automatic symbol naming.

TPU-native counterpart of the reference's NameManager
(python/mxnet/name.py): a thread-local stack of managers hands out unique
names per op type ("fullyconnected0", ...) and ``Prefix`` prepends a scope
prefix, so composed graphs get stable, human-readable node names.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """Hands out unique auto-names per hint; usable as a ``with`` scope."""

    _current = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        """Return ``name`` if given, else a fresh auto-name for ``hint``."""
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        self._old_manager = NameManager.current()
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        NameManager._current.value = self._old_manager

    @staticmethod
    def current() -> "NameManager":
        cur = getattr(NameManager._current, "value", None)
        if cur is None:
            cur = NameManager()
            NameManager._current.value = cur
        return cur


class Prefix(NameManager):
    """NameManager that prepends a fixed prefix to every name."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
