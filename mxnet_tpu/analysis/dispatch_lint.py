"""Dispatch-discipline analyzer — the GL7xx family.

PyGraph's observation (PAPERS.md) is that small-kernel work-loops are
priced by per-launch CPU overhead, not device compute; the decode loop in
``serving/kv_decode.py`` is the canonical shape: one executable dispatch
per token with a device->host pull in between, so the TPU idles for the
host round-trip every step. No Symbol-level pass can see that seam — it
lives in the *call sites*, not the graph — so this family has three legs:

  * a source-level lint (``lint_dispatch_paths``) that walks the Python
    call sites with ``ast`` and diagnoses the loop shapes: GL701
    host-sync-inside-loop, GL702 scan-able per-iteration dispatch (with a
    modeled dispatches-saved estimate), GL703 host-side reduction with an
    on-device lowering, GL704 premature blocking pull that serializes an
    async dispatch chain;
  * a graph pass (``dispatch_lint``) on the shared ``GraphContext`` walk
    that flags decode-signature Symbols (loop-carried KV outputs plus a
    full-logits head) with no on-device token reduction — the graph-side
    face of GL703, run at ``executor.bind`` / SPMD bind under
    ``MXNET_GRAPHLINT`` like every other family;
  * a measured lint (``lint_dispatch_gaps``) over the telemetry
    ``dispatch.host_gap`` attribution: GL705 when the host gap between an
    executable's return and the next enqueue exceeds
    ``MXNET_DISPATCHLINT_GAP_PCT`` of device busy time.

Acknowledged sites carry an inline waiver comment::

    x = exe.outputs[0].asnumpy()  # graphlint: waive GL703 -- reason

on the finding's line (or the line above). Waived findings stay in the
site table but do not fail the run. ``GL7xx`` waives the whole family.
"""
from __future__ import annotations

import ast
import logging
import os
import re
from typing import Dict, List, Optional, Tuple

from .diagnostics import Diagnostic, Report
from .manager import graph_pass
# registration order IS run order: the graph-side pass below reads
# ctx.entry_shape/var_shape, which shape_lint fills — import it first so
# an eager ``from analysis import dispatch_lint`` cannot register us ahead
# of it
from . import shape_lint  # noqa: F401

__all__ = ["lint_dispatch_paths", "lint_dispatch_source",
           "lint_dispatch_gaps", "dispatch_gap_pct", "DEFAULT_SCAN_PATHS"]

_log = logging.getLogger("mxnet_tpu.graphlint")

# call-site vocabulary ------------------------------------------------------
# a method call by one of these names enqueues device work. The megastep
# entry points (serving/kv_decode.py decode_megastep/step_megastep) are
# dispatches too — K tokens per call, but still one host round-trip each,
# so a loop over them is a (K-amortized) GL701 site.
_DISPATCH_NAMES = frozenset({"forward", "decode_step", "greedy_step",
                             "step", "prefill", "run",
                             "decode_megastep", "step_megastep"})
# a call by one of these names blocks on a device->host transfer
_PULL_NAMES = frozenset({"asnumpy", "block_until_ready", "item", "tolist"})
# host reductions numpy performs that sym.* can lower on device instead
_HOST_REDUCERS = frozenset({"argmax", "argmin", "argsort", "argpartition",
                            "choice"})  # np.random.choice = host sampling
# on-device reduction ops: their presence in a graph clears graph-side GL703
_DEVICE_ARG_OPS = frozenset({"argmax", "argmin", "argmax_channel", "topk",
                             "sample_multinomial", "multinomial"})
# loss heads: a training symbol's non-carry output, never a logits head a
# decoder would reduce on host
_LOSS_OPS = frozenset({"SoftmaxOutput", "LinearRegressionOutput",
                       "LogisticRegressionOutput", "MAERegressionOutput",
                       "MakeLoss", "softmax_cross_entropy"})

# default source-scan surface: the serving hot paths plus the benches that
# drive them. Model zoo code never dispatches in a loop, so it is not
# scanned — the graph pass covers Symbols.
DEFAULT_SCAN_PATHS = ("mxnet_tpu/serving", "tools/serve_bench.py",
                      "bench.py")

_WAIVE_RE = re.compile(r"#\s*graphlint:\s*waive\s+([A-Za-z0-9, x]+)")

_warned_pcts: set = set()


def dispatch_gap_pct(default: float = 0.25) -> float:
    """GL705 threshold: host gap as a fraction of device busy time
    (``MXNET_DISPATCHLINT_GAP_PCT``, default 0.25)."""
    raw = os.environ.get("MXNET_DISPATCHLINT_GAP_PCT", "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
        if val <= 0:
            raise ValueError
        return val
    except ValueError:
        if raw not in _warned_pcts:
            _warned_pcts.add(raw)
            _log.warning("MXNET_DISPATCHLINT_GAP_PCT=%r is not a positive "
                         "number; using %.2f", raw, default)
        return default


# --------------------------------------------------------------------------
# source-level analysis
# --------------------------------------------------------------------------

def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _base_name(expr) -> Optional[str]:
    """Leftmost Name of an attribute/subscript chain: exe.outputs[0] -> exe."""
    while isinstance(expr, (ast.Attribute, ast.Subscript, ast.Call)):
        expr = expr.func if isinstance(expr, ast.Call) else expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _walk_shallow(node):
    """Walk ``node`` without descending into nested function/class defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


class _FuncFacts:
    """Per-function call inventory, one level of the module call graph."""

    def __init__(self, qualname: str, node):
        self.qualname = qualname
        self.node = node
        self.pulls: List[Tuple[int, str]] = []       # (line, pull name)
        self.dispatches: List[Tuple[int, str]] = []  # (line, call name)
        for n in _walk_shallow(node):
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name in _PULL_NAMES:
                self.pulls.append((n.lineno, name))
            elif name in _DISPATCH_NAMES:
                self.dispatches.append((n.lineno, name))


def _collect_functions(tree) -> Dict[str, _FuncFacts]:
    """qualname -> facts; methods indexed under both Class.meth and meth
    (``self.decode_step(...)`` resolves by bare name)."""
    out: Dict[str, _FuncFacts] = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = prefix + child.name
                facts = _FuncFacts(q, child)
                out[q] = facts
                out.setdefault(child.name, facts)
            elif isinstance(child, ast.ClassDef):
                visit(child, child.name + ".")

    visit(tree, "")
    return out


def _range_trip_count(loop) -> Optional[str]:
    """Human trip-count of ``for _ in range(...)``: a literal, a name, or
    None when the loop is not range-shaped (while loops, iterators)."""
    if not isinstance(loop, ast.For):
        return None
    it = loop.iter
    if isinstance(it, ast.Call) and _call_name(it) == "range" and it.args:
        last = it.args[-1]
        if isinstance(last, ast.Constant) and isinstance(last.value, int):
            return str(last.value)
        if isinstance(last, ast.Name):
            return last.id
        if isinstance(last, ast.Attribute):
            return ast.unparse(last) if hasattr(ast, "unparse") else last.attr
    return None


def _load_waivers(text: str) -> Dict[int, set]:
    """line -> set of waived codes; a waiver covers its own line and the
    line below (comment-above style)."""
    waivers: Dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _WAIVE_RE.search(line)
        if not m:
            continue
        codes = {c.strip().upper() for c in m.group(1).split(",") if c.strip()}
        waivers.setdefault(i, set()).update(codes)
        waivers.setdefault(i + 1, set()).update(codes)
    return waivers


def _is_waived(waivers: Dict[int, set], line: int, code: str) -> bool:
    at = waivers.get(line, ())
    return code in at or "GL7XX" in at


class _Finding:
    """One dispatch-lint site: a Diagnostic plus table metadata."""

    def __init__(self, code, path, line, function, message, fix_hint=None,
                 provenance=None, waived=False):
        self.code = code
        self.path = path
        self.line = line
        self.function = function
        self.message = message
        self.fix_hint = fix_hint
        self.provenance = list(provenance or [])
        self.waived = waived

    @property
    def site(self) -> str:
        return "%s:%d" % (self.path, self.line)

    def to_diagnostic(self) -> Diagnostic:
        msg = self.message
        if self.waived:
            msg += " [waived]"
        return Diagnostic(self.code, msg, node=self.site,
                          fix_hint=self.fix_hint, provenance=self.provenance,
                          pass_name="dispatch_lint",
                          severity="info" if self.waived else None)

    def to_dict(self) -> dict:
        return {"code": self.code, "file": self.path, "line": self.line,
                "function": self.function, "message": self.message,
                "fix_hint": self.fix_hint, "waived": self.waived,
                "provenance": list(self.provenance)}


def lint_dispatch_source(path: str, text: Optional[str] = None
                         ) -> List[_Finding]:
    """Static GL701-GL704 over one Python source file.

    The analysis is a module-local call graph (one level deep: a loop that
    calls ``self.decode_step`` inherits decode_step's pulls/dispatches) —
    exactly deep enough for the decoder/bench loop shapes without whole-
    program inference."""
    if text is None:
        with open(path) as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as exc:
        return [_Finding("GL704", path, exc.lineno or 1, "<module>",
                         "unparseable source: %s" % exc, waived=False)]
    waivers = _load_waivers(text)
    funcs = _collect_functions(tree)
    findings: List[_Finding] = []
    seen = set()

    def add(code, line, function, message, fix_hint=None, provenance=None):
        key = (code, line)
        if key in seen:
            return
        seen.add(key)
        findings.append(_Finding(
            code, path, line, function, message, fix_hint=fix_hint,
            provenance=provenance, waived=_is_waived(waivers, line, code)))

    for facts in {id(f): f for f in funcs.values()}.values():
        _lint_function(facts, funcs, add)
    findings.sort(key=lambda f: (f.line, f.code))
    return findings


def _lint_function(facts: _FuncFacts, funcs, add):
    fn = facts.node
    # ---- GL701 / GL702: loop shapes -------------------------------------
    for loop in _walk_shallow(fn):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        pulls: List[Tuple[int, List[str]]] = []     # (line, provenance)
        dispatches: List[Tuple[int, str, object]] = []  # (line, label, call)
        assigned: Dict[str, set] = {}               # name -> names it reads
        for n in _walk_shallow(loop):
            if isinstance(n, ast.Assign):
                reads = _names_in(n.value)
                for tgt in n.targets:
                    for t in ast.walk(tgt):
                        if isinstance(t, ast.Name):
                            assigned.setdefault(t.id, set()).update(reads)
            if not isinstance(n, ast.Call):
                continue
            name = _call_name(n)
            if name in _PULL_NAMES:
                pulls.append((n.lineno, []))
            elif name in _DISPATCH_NAMES:
                dispatches.append((n.lineno, name, n))
                callee = funcs.get(name)
                if callee is not None and callee.node is not fn:
                    # one level of the module call graph: the loop inherits
                    # the callee's host syncs
                    for pline, pname in callee.pulls:
                        pulls.append((pline, [
                            "%s() pulls to host at line %d (%s)"
                            % (callee.qualname, pline, pname),
                            "called from the loop at line %d in %s"
                            % (n.lineno, facts.qualname)]))
        if dispatches and pulls:
            for pline, prov in pulls:
                add("GL701", pline, facts.qualname,
                    "device->host pull inside the dispatch loop at line %d "
                    "(%s): the pulled value gates the next iteration's "
                    "dispatch, so the device idles for a host round-trip "
                    "every step" % (loop.lineno, facts.qualname),
                    fix_hint="keep the loop state on device and fold the "
                    "loop into one lax.scan megastep (ROADMAP: "
                    "device-resident decode)",
                    provenance=prov)
        if dispatches:
            # loop-carried state, strictly: some argument of a dispatch
            # reads (transitively through in-loop assignments) a name that
            # holds a dispatch result — `logits = step(tok); tok = f(logits)`.
            # Merely assigning things in a loop that also dispatches (warmup
            # loops, retry loops) is not scan-able.
            results = set()
            for n in _walk_shallow(loop):
                if isinstance(n, ast.Assign) and any(
                        isinstance(c, ast.Call)
                        and _call_name(c) in _DISPATCH_NAMES
                        for c in ast.walk(n.value)):
                    for tgt in n.targets:
                        for t in ast.walk(tgt):
                            if isinstance(t, ast.Name):
                                results.add(t.id)

            def _reaches_result(name):
                stack, visited = [name], set()
                while stack:
                    cur = stack.pop()
                    if cur in visited:
                        continue
                    visited.add(cur)
                    if cur in results:
                        return True
                    stack.extend(assigned.get(cur, ()))
                return False

            carried = any(
                _reaches_result(an)
                for _, _, call in dispatches
                for a in list(call.args) + [kw.value for kw in call.keywords]
                for an in _names_in(a))
            if carried:
                trips = _range_trip_count(loop)
                saved = ("~%s-1 dispatches -> 1" % trips) if trips else \
                    "N-1 of N per-iteration dispatches"
                dline = dispatches[0][0]
                add("GL702", dline, facts.qualname,
                    "per-iteration executable dispatch with loop-carried "
                    "state (loop at line %d); a lax.scan megastep saves "
                    "%s" % (loop.lineno, saved),
                    fix_hint="rewrite the loop body as a scan step: carry "
                    "the loop state as scan carries, dispatch once")
    # ---- GL703: host reduction of a device output -----------------------
    # names assigned (anywhere in the function) from a dispatch or a pull
    device_derived: Dict[str, Tuple[int, str]] = {}
    for n in _walk_shallow(fn):
        if not isinstance(n, ast.Assign):
            continue
        for c in ast.walk(n.value):
            if isinstance(c, ast.Call) and \
                    _call_name(c) in (_DISPATCH_NAMES | _PULL_NAMES):
                origin = "%s() at line %d" % (_call_name(c), c.lineno)
                for tgt in n.targets:
                    for t in ast.walk(tgt):
                        if isinstance(t, ast.Name):
                            device_derived[t.id] = (c.lineno, origin)
    for n in _walk_shallow(fn):
        if not (isinstance(n, ast.Call) and _call_name(n) in _HOST_REDUCERS):
            continue
        arg_names = set()
        for a in list(n.args) + [kw.value for kw in n.keywords]:
            arg_names |= _names_in(a)
        inline_pull = any(
            isinstance(c, ast.Call) and _call_name(c) in _PULL_NAMES
            for a in n.args for c in ast.walk(a))
        hits = sorted(an for an in arg_names if an in device_derived)
        if not hits and not inline_pull:
            continue
        prov = ["%s derives from %s" % (an, device_derived[an][1])
                for an in hits]
        add("GL703", n.lineno, facts.qualname,
            "host-side %s() of a device output; sym.%s lowers the same "
            "reduction on device, so the host need only pull the reduced "
            "result" % (_call_name(n), _call_name(n)
                        if _call_name(n) != "choice" else "multinomial"),
            fix_hint="add the reduction to the executable's outputs and "
            "pull the (tiny) reduced array instead of the full tensor",
            provenance=prov)
    # ---- GL704: premature blocking pull between independent dispatches --
    _lint_premature_pull(facts, add)


def _lint_premature_pull(facts: _FuncFacts, add):
    """Straight-line shape: dispatch on A, blocking pull of A's output,
    then a dispatch on B that does not consume the pulled value — the pull
    serializes B behind A's device completion for no reason."""
    events = []  # (line, kind, base, result_names, arg_names)
    for stmt in _walk_shallow(facts.node):
        if isinstance(stmt, (ast.For, ast.While)):
            return  # loop bodies belong to GL701/GL702
        if not isinstance(stmt, ast.Assign):
            if isinstance(stmt, ast.Expr):
                stmt_val = stmt.value
                targets = []
            else:
                continue
        else:
            stmt_val = stmt.value
            targets = [t.id for tgt in stmt.targets
                       for t in ast.walk(tgt) if isinstance(t, ast.Name)]
        for c in ast.walk(stmt_val):
            if not isinstance(c, ast.Call):
                continue
            name = _call_name(c)
            if name in _DISPATCH_NAMES:
                events.append((c.lineno, "dispatch",
                               _base_name(c.func), set(targets),
                               _names_in(c)))
            elif name in _PULL_NAMES:
                events.append((c.lineno, "pull",
                               _base_name(c.func), set(targets), set()))
    events.sort(key=lambda e: e[0])
    dispatched_bases = {}
    for i, (line, kind, base, results, _args) in enumerate(events):
        if kind == "dispatch":
            dispatched_bases[base] = line
            for r in results:
                dispatched_bases[r] = line
            continue
        if base not in dispatched_bases:
            continue
        for lline, lkind, lbase, _lres, largs in events[i + 1:]:
            if lkind == "dispatch" and lbase != base \
                    and not (results & largs):
                add("GL704", line, facts.qualname,
                    "blocking pull of %r (dispatched at line %d) before "
                    "the independent dispatch at line %d: the pull "
                    "serializes an async dispatch chain"
                    % (base, dispatched_bases[base], lline),
                    fix_hint="enqueue the independent dispatch first, "
                    "then pull; device queues overlap the transfer")
                break


def _iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif os.path.exists(p):
            yield p
        else:
            raise OSError("dispatch-lint path does not exist: %s" % p)


def lint_dispatch_paths(paths=None, root: Optional[str] = None
                        ) -> Tuple[Report, List[dict]]:
    """Run the source-level dispatch lint over ``paths`` (files or
    directories; default ``DEFAULT_SCAN_PATHS`` resolved against ``root``
    or the repo checkout this package sits in).

    Returns ``(Report, site rows)``; waived findings are severity-info in
    the report (they never fail a run) and ``"waived": true`` in the rows.
    """
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if paths is None:
        paths = [os.path.join(root, p) for p in DEFAULT_SCAN_PATHS]
        paths = [p for p in paths if os.path.exists(p)]
    report = Report(target="dispatch")
    sites: List[dict] = []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path, root) if os.path.isabs(path) else path
        for f in lint_dispatch_source(path):
            f.path = rel
            report.add(f.to_diagnostic())
            sites.append(f.to_dict())
    return report, sites


# --------------------------------------------------------------------------
# measured side: GL705 over the dispatch.host_gap attribution
# --------------------------------------------------------------------------

def lint_dispatch_gaps(gap_rows, pct: Optional[float] = None,
                       min_intervals: int = 2) -> List[Diagnostic]:
    """GL705 over ``telemetry.gap_summary`` rows (``{"name", "count",
    "busy_ms", "gap_ms", "intervals", "max_gap_ms"}``): flag a call site
    whose summed host gap exceeds ``pct`` (default
    ``MXNET_DISPATCHLINT_GAP_PCT``) of its device busy time."""
    if pct is None:
        pct = dispatch_gap_pct()
    out: List[Diagnostic] = []
    for row in gap_rows:
        if row.get("intervals", 0) < min_intervals:
            continue
        busy = float(row.get("busy_ms", 0.0))
        gap = float(row.get("gap_ms", 0.0))
        if busy <= 0.0 or gap <= pct * busy:
            continue
        out.append(Diagnostic(
            "GL705",
            "measured host gap at %r: %.3f ms across %d intervals = "
            "%.0f%% of %.3f ms device busy time (threshold %.0f%%)"
            % (row.get("name"), gap, row.get("intervals", 0),
               100.0 * gap / busy, busy, 100.0 * pct),
            node=row.get("name"),
            fix_hint="the host gates every dispatch at this site; batch "
            "the host work or fold the loop on device (lax.scan)",
            pass_name="dispatch_lint"))
    return out


# --------------------------------------------------------------------------
# graph-side GL703: decode-signature Symbol without an on-device token head
# --------------------------------------------------------------------------

def _carry_outputs(ctx):
    """Output indices that are loop-carried state: the producer's input
    chain (short walk) contains a *variable* whose inferred shape equals
    the output's — the KV write-back pattern ``kv' = f(kv, ...)``."""
    carries = []
    outputs = getattr(ctx.symbol, "_outputs", None)
    if not outputs:
        return carries
    for oi, (node, out_idx) in enumerate(outputs):
        oshape = ctx.entry_shape.get((id(node), out_idx))
        if oshape is None or node.is_variable:
            continue
        frontier, seen, found = [node], set(), False
        for _depth in range(8):
            if not frontier or found:
                break
            nxt = []
            for n in frontier:
                for inp, _ii in n.inputs:
                    if id(inp) in seen:
                        continue
                    seen.add(id(inp))
                    if inp.is_variable:
                        vshape = ctx.var_shape.get(inp.name)
                        if vshape is not None and \
                                tuple(vshape) == tuple(oshape):
                            found = True
                    else:
                        nxt.append(inp)
            frontier = nxt
        if found:
            carries.append(oi)
    return carries


@graph_pass("dispatch_lint")
def dispatch_lint_pass(ctx):
    """Graph-side GL703: a decode-signature Symbol — >=2 loop-carried
    (KV) outputs plus a non-carry, non-loss float head — with no on-device
    arg-reduction anywhere in the graph forces its driver to pull the full
    head tensor and reduce on host every step."""
    diags: List[Diagnostic] = []
    ops = {n.op for n in ctx.topo if not n.is_variable}
    if ops & _DEVICE_ARG_OPS:
        return diags
    carries = set(_carry_outputs(ctx))
    if len(carries) < 2:
        return diags
    outputs = ctx.symbol._outputs
    for oi, (node, out_idx) in enumerate(outputs):
        if oi in carries or node.is_variable or node.op in _LOSS_OPS:
            continue
        sh = ctx.entry_shape.get((id(node), out_idx))
        if sh is None or len(sh) < 2:
            continue
        diags.append(Diagnostic(
            "GL703",
            "decode-signature symbol (%d loop-carried output(s)) exposes "
            "the full %s head %r with no on-device reduction: greedy "
            "decode will pull %s floats per step and argmax on host"
            % (len(carries), "x".join(map(str, sh)), ctx.node_label(node),
               "x".join(map(str, sh))),
            node=ctx.node_label(node), op=node.op,
            fix_hint="append sym.argmax(head, axis=-1) to the output "
            "group (models.transformer.get_decode_symbol token_out=True) "
            "so the host pulls one id per stream",
            provenance=ctx.provenance(node, depth=2, max_lines=4)))
        break  # one finding per symbol: the head, not every output
    return diags
