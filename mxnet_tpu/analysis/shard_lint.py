"""Sharding-plan lint (GL401–GL405).

PR 1's passes lint the single-device graph; this one lints the *distributed
execution plan*: given a mesh (axis names/sizes — an abstract
``parallel.mesh.MeshSpec`` or a real jax Mesh) and
``parallel.sharding.ShardingRules``, it propagates per-entry PartitionSpecs
through the op semantics declared in ``ops/infer_meta.py`` (``shard_rule``
categories) and diagnoses the plan XLA would otherwise "fix" silently with
collectives — the implicit-resharding tax of *Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training* (PAPERS.md), surfaced
before a single compile:

  GL401  a rank-2 parameter large enough to shard has NO dim divisible by
         the model axis — the rule silently fell back to full replication
  GL402  an implicit reshard edge: a producer's sharded layout must be
         gathered (or re-laid-out) to satisfy a consumer, with an analytic
         bytes-moved-per-device estimate for the edge
  GL403  batch-axis loss: an op collapses the data-sharded dim mid-graph,
         forcing a full gather of everything downstream
  GL404  a sharded dim does not divide its mesh-axis factor — XLA pads
         every shard (wasted HBM + compute on padding)
  GL405  a large replicated parameter the default rule (``param_pspec``)
         could shard — the fix hint names the rule

The propagated specs land in ``ctx.entry_spec`` (per-dim tuples of mesh axis
names), which the GL5xx memory planner consumes for per-device byte
accounting. The cost model for a gather: all-gathering a tensor sharded
``f`` ways makes every device receive ``(f-1)/f`` of the global bytes.
"""
from __future__ import annotations

import numpy as np

from ..ops.infer_meta import get_meta
from .diagnostics import Diagnostic
from .manager import GraphContext, graph_pass
from .retrace_guard import _data_like_vars

__all__ = ["shard_plan_lint", "batch_like_vars", "norm_spec", "spec_factor",
           "entry_bytes", "fmt_bytes"]

_EDGE_CAP = 8          # per-edge GL402 diagnostics before summarizing
_SUMMARY_CAP = 32      # provenance rows in the overflow summary

# the reference's NameManager parameter-suffix convention: a variable whose
# auto-generated name ends in one of these is a learned parameter even when
# it reaches the graph through a generic op (LayerNorm gamma via
# broadcast_mul, positional embeddings via broadcast_add, attention
# projections via dot) — infer_meta's param_slots cannot see those
_PARAM_SUFFIXES = ("weight", "bias", "gamma", "beta",
                   "moving_mean", "moving_var", "running_mean", "running_var")


def batch_like_vars(ctx):
    """Arg variables that carry per-batch data (inputs/labels/masks) under
    the sharding plan. Starts from the retrace guard's data-like set (vars
    feeding any non-param slot) and removes the parameter-named ones the
    slot heuristic misclassifies. Known trade-off: a *data* input named
    with a param suffix (e.g. a per-example ``sample_weight``) is planned
    as a parameter — the rarer mistake than batch-sharding every LayerNorm
    gamma and positional embedding, and fixable by renaming the input."""
    return [n for n in _data_like_vars(ctx)
            if not n.name.endswith(_PARAM_SUFFIXES)]


# --------------------------------------------------------------------- bytes
def norm_spec(pspec, rank):
    """Normalize a jax PartitionSpec / tuple to per-dim tuples of axis
    names, padded to ``rank``: ``P('data', None)`` → ``(('data',), ())``."""
    out = []
    seq = tuple(pspec) if pspec is not None else ()
    for i in range(rank):
        e = seq[i] if i < len(seq) else None
        if e is None:
            out.append(())
        elif isinstance(e, (list, tuple)):
            out.append(tuple(e))
        else:
            out.append((e,))
    return tuple(out)


def _replicated(rank):
    return ((),) * rank


def _axis_size(mesh, axis):
    return int(mesh.shape[axis]) if axis in mesh.shape else 1


def spec_factor(spec, mesh, dim=None):
    """Total shard count of a normalized spec (or of one dim)."""
    dims = spec if dim is None else (spec[dim],)
    f = 1
    for axes in dims:
        for a in axes:
            f *= _axis_size(mesh, a)
    return f


def _itemsize(dtype):
    try:
        return np.dtype(dtype).itemsize if dtype is not None else 4
    except TypeError:
        return 4


def entry_bytes(shape, dtype, spec, mesh):
    """Per-device bytes of one tensor under its (normalized) spec."""
    total = int(np.prod(shape)) * _itemsize(dtype) if shape else _itemsize(dtype)
    return total // max(1, spec_factor(spec, mesh))


def fmt_bytes(n):
    for unit, div in (("GiB", 2 ** 30), ("MiB", 2 ** 20), ("KiB", 2 ** 10)):
        if n >= div:
            return "%.2f %s" % (n / div, unit)
    return "%d B" % n


def _spec_str(spec):
    if not any(spec):
        return "[replicated]"
    return "[" + ",".join("/".join(a) if a else "." for a in spec) + "]"


# ---------------------------------------------------------------- propagation
def _merge_dim(a, b):
    """Merge two per-dim axis tuples: equal or one empty → the union wins;
    a true conflict returns None (caller gathers one side)."""
    if a == b or not b:
        return a
    if not a:
        return b
    return None


def _resolve_reduce_axes(parsed, ndim):
    """Mirror ops/broadcast_reduce axis resolution: () means every dim."""
    ax = parsed.get("axis", ())
    if ax is None:
        ax = ()
    if isinstance(ax, (int, np.integer)):
        ax = (int(ax),)
    ax = tuple(int(a) % ndim for a in ax)
    if not ax:
        ax = tuple(range(ndim))
    if parsed.get("exclude"):
        ax = tuple(i for i in range(ndim) if i not in ax)
    return set(ax)


def _propagate_node(node, parsed, meta, in_specs, in_shapes, out_shapes):
    """Compute the output specs of ``node`` and the gathers it forces.

    Returns (out_specs, gathers) where gathers is a list of
    (input_index, dims, why). Specs are normalized per-dim tuples; a spec of
    None means the input's spec/shape was unknown (treated replicated)."""
    rank_of = [len(s) if s is not None else 0 for s in in_shapes]
    specs = [s if s is not None else _replicated(r)
             for s, r in zip(in_specs, rank_of)]
    gathers = []

    def gather(i, dims, why):
        dims = [d for d in dims if d < len(specs[i]) and specs[i][d]]
        if dims:
            gathers.append((i, dims, why))
            specs[i] = tuple(() if d in dims else a
                             for d, a in enumerate(specs[i]))

    def out_like(template):
        return [tuple(template)[: len(sh)] + _replicated(
            max(0, len(sh) - len(template))) if sh is not None else None
            for sh in out_shapes]

    rule = meta.shard_rule

    if rule == "elementwise":
        out_rank = max([len(sh) for sh in out_shapes if sh is not None] or [0])
        out_sh = next((sh for sh in out_shapes
                       if sh is not None and len(sh) == out_rank), None)
        merged = list(_replicated(out_rank))
        # align by trailing dims (numpy broadcasting); a dim an input
        # truly broadcasts over (extent 1 vs a larger output extent)
        # contributes nothing — but an extent-1 dim that STAYS extent 1
        # (batch=1 over a dp axis) must keep its sharding
        sized = sorted(range(len(specs)),
                       key=lambda i: -(int(np.prod(in_shapes[i]))
                                       if in_shapes[i] else 0))
        for i in sized:
            sh, sp = in_shapes[i], specs[i]
            if sh is None:
                continue
            off = out_rank - len(sh)
            for d in range(len(sh)):
                if (sh[d] == 1 and out_sh is not None
                        and out_sh[off + d] != 1):
                    continue
                m = _merge_dim(merged[off + d], sp[d])
                if m is None:
                    gather(i, [d], "layout conflict with a larger operand")
                else:
                    merged[off + d] = m
        out = [tuple(merged[: len(sh)]) if sh is not None and len(sh) == out_rank
               else (tuple(merged[-len(sh):]) if sh is not None else None)
               for sh in out_shapes]
        return out, gathers

    if rule in ("conv", "fc", "dot", "batch_dot"):
        if rule == "conv" and len(specs) == 1:
            # windowed single-input op (Pooling): batch + channel sharding
            # survive, spatial dims must be whole
            dspec = specs[0]
            gather(0, range(2, len(dspec)),
                   "spatial dims must be whole for the pooling window")
            return out_like(specs[0][:2]), gathers
        if len(specs) < 2 or in_shapes[0] is None or in_shapes[1] is None:
            return out_like(specs[0][:1] if specs else ()), gathers
        dspec, wspec = specs[0], specs[1]
        if rule == "conv":
            # data (B,C,H,W) ⊗ weight (N,K,kh,kw) → (B,N,H',W')
            gather(0, range(2, len(dspec)), "spatial dims must be whole for "
                                            "the convolution window")
            if dspec[1] != wspec[1]:
                i = 0 if dspec[1] else 1
                gather(i, [1], "contraction (channel) dim sharded on one "
                               "side only")
            batch, outc = specs[0][0], specs[1][0]
            return out_like((batch, outc)), gathers
        if rule == "fc":
            # data (B, k...) ⊗ weight (N, K) → (B, N); trailing data dims
            # flatten into the contraction
            contract_data = tuple(sorted({a for ax in dspec[1:] for a in ax}))
            contract_w = tuple(sorted(set(wspec[1]))) if len(wspec) > 1 else ()
            if contract_data != contract_w:
                if contract_data:
                    gather(0, range(1, len(dspec)),
                           "contraction dim sharded on the data side only")
                if contract_w:
                    gather(1, [1], "contraction dim sharded on the weight "
                                   "side only")
            return out_like((specs[0][0], specs[1][0])), gathers
        if rule == "dot":
            if len(dspec) > 1 and dspec[-1] != (wspec[0] if wspec else ()):
                i = 0 if dspec[-1] else 1
                gather(i, [len(specs[i]) - 1 if i == 0 else 0],
                       "dot contraction dim sharded on one side only")
            d0 = dspec[0] if len(dspec) > 1 else ()
            w1 = wspec[1] if len(wspec) > 1 else ()
            return out_like((d0, w1)), gathers
        # batch_dot (b,m,k) ⊗ (b,k,n) → (b,m,n)
        b = _merge_dim(dspec[0], wspec[0])
        if b is None:
            gather(1, [0], "batch dims sharded differently")
            b = dspec[0]
        if dspec[2] != wspec[1]:
            i = 0 if dspec[2] else 1
            gather(i, [2 if i == 0 else 1],
                   "batch_dot contraction dim sharded on one side only")
        return out_like((b, dspec[1], wspec[2])), gathers

    if rule in ("embedding", "row_sparse_embedding"):
        # data (B,...) rows of weight (V, D) → (B, ..., D). A vocab-sharded
        # table serves the lookup with a masked-sum psum whose traffic is
        # the OUTPUT, not the table — modeled as a gather of the output dim.
        # The row_sparse variant's backward mirrors it: only touched rows
        # scatter back, so the same output-bytes pricing holds both ways
        # (docs/SPARSE.md) — which is why a sharded table falls out of
        # autoplan's search instead of being taxed a full-table gather.
        dspec = specs[0] if specs else ()
        wspec = specs[1] if len(specs) > 1 else _replicated(2)
        if len(wspec) > 0 and wspec[0]:
            gathers.append((1, [0], "vocab-sharded table: the lookup psums "
                                    "the full output on every device"))
        d_dim = wspec[1] if len(wspec) > 1 else ()
        return out_like(tuple(dspec) + (d_dim,)), gathers

    if rule == "flatten":
        dspec = specs[0] if specs else ()
        gather(0, range(1, len(dspec)),
               "flatten collapses these dims into one")
        return out_like((specs[0][0] if specs and specs[0] else (),)), gathers

    if rule == "reshape":
        dspec = specs[0] if specs else ()
        ish = in_shapes[0]
        osh = out_shapes[0] if out_shapes else None
        # dim 0 sharding survives when out dim 0 is a row-major merge of the
        # leading input dims (B,T,C -> B*T,C keeps the outer-dim split);
        # anything else — splits, transpath merges — is conservatively a
        # full re-partition
        keep0 = False
        if ish and osh:
            lead = 1
            for k in range(len(ish)):
                lead *= ish[k]
                if lead == osh[0]:
                    keep0 = True
                    break
                if lead > osh[0]:
                    break
        gather(0, range(1 if keep0 else 0, len(dspec)),
               "reshape re-partitions these dims")
        return out_like((dspec[0],) if keep0 and dspec else ()), gathers

    if rule == "transpose":
        dspec = specs[0] if specs else ()
        axes = parsed.get("axes", ()) or tuple(reversed(range(len(dspec))))
        try:
            out0 = tuple(dspec[int(a)] for a in axes)
        except (IndexError, ValueError):
            out0 = _replicated(len(dspec))
        return out_like(out0), gathers

    if rule == "concat":
        cat = int(parsed.get("dim", 1))
        out_rank = len(out_shapes[0]) if out_shapes and out_shapes[0] else 0
        cat %= max(1, out_rank)
        merged = list(_replicated(out_rank))
        for i, sp in enumerate(specs):
            if len(sp) != out_rank:
                continue
            gather(i, [cat], "concat dim must be whole to interleave")
            sp = specs[i]
            for d in range(out_rank):
                if d == cat:
                    continue
                m = _merge_dim(merged[d], sp[d])
                if m is None:
                    gather(i, [d], "layout conflict across concat inputs")
                else:
                    merged[d] = m
        return out_like(tuple(merged)), gathers

    if rule == "reduce":
        dspec = specs[0] if specs else ()
        ndim = len(dspec)
        red = _resolve_reduce_axes(parsed, ndim) if ndim else set()
        keep = bool(parsed.get("keepdims", False))
        # reducing over a sharded dim is an efficient psum (traffic = output
        # bytes), not a reshard — so no gather is recorded for those dims
        out0 = tuple(dspec[d] if d not in red else ()
                     for d in range(ndim)
                     if keep or d not in red)
        return out_like(out0), gathers

    if rule == "softmax":
        dspec = specs[0] if specs else ()
        gather(0, range(1, len(dspec)),
               "softmax normalizes over the full non-batch extent")
        return out_like((dspec[0] if dspec else (),)), gathers

    # ---- default "batch0": keep the batch-dim sharding when dim 0's extent
    # survives; everything else is assumed to need whole operands
    for i in range(len(specs)):
        gather(i, range(1, len(specs[i])),
               "op %r has no declared sharding semantics: non-batch dims "
               "are assumed gathered" % node.op)
    d0 = ()
    if (specs and in_shapes[0] is not None and len(in_shapes[0]) >= 1
            and out_shapes and out_shapes[0] is not None
            and len(out_shapes[0]) >= 1
            and out_shapes[0][0] == in_shapes[0][0]):
        d0 = specs[0][0]
    return out_like((d0,)), gathers


# --------------------------------------------------------------------- pass
@graph_pass("shard_lint")
def shard_plan_lint(ctx: GraphContext):
    if ctx.mesh is None or ctx.rules is None:
        return []
    from ..parallel.mesh import MeshSpec
    from ..parallel.sharding import (MIN_SHARD_ELEMS, param_pspec,
                                     shardable_dims)

    mesh = MeshSpec.of(ctx.mesh)
    rules = ctx.rules
    model_size = rules.model_parallel_size
    diags = []

    # ---- seed variable specs (and GL401/GL404/GL405 on params) ----------
    data_like = {n.name for n in batch_like_vars(ctx)}
    aux_names = {n.name for n in ctx.aux_nodes}
    # variables consumed as an embedding TABLE (slot 1 of an embedding-
    # category op): GL405's fix hint names the table-specific placement
    # instead of the generic rank-2 advice
    from ..ops.infer_meta import EMBEDDING_RULES

    embed_tables = {}
    for node in ctx.topo:
        if node.is_variable or len(node.inputs) < 2:
            continue
        if get_meta(node.op).shard_rule in EMBEDDING_RULES:
            wnode = node.inputs[1][0]
            if wnode.is_variable:
                embed_tables.setdefault(wnode.name, (node.name, node.op))
    for node in ctx.arg_nodes + ctx.aux_nodes:
        shape = ctx.var_shape.get(node.name)
        if shape is None:
            continue
        if node.name in aux_names:
            spec = _replicated(len(shape))
        elif node.name in data_like:
            spec = norm_spec(rules.batch_spec(shape), len(shape))
        else:
            spec = norm_spec(rules.param_spec(node.name, shape), len(shape))
            if not any(spec) and model_size > 1:
                elems = int(np.prod(shape))
                default = norm_spec(
                    param_pspec(node.name, shape, rules.model_axis or "model",
                                model_size), len(shape))
                if any(default):
                    if node.name in embed_tables:
                        consumer, op = embed_tables[node.name]
                        hint = ("%r is the embedding table of %s (%s): "
                                "param_pspec(%r, %s, model_axis=%r, "
                                "model_size=%d) shards its vocab dim over "
                                "the model axis — the lookup then psums "
                                "only the output rows%s. Drop the custom "
                                "param_rule for this name or return that "
                                "spec."
                                % (node.name, consumer, op, node.name,
                                   tuple(shape), rules.model_axis or "model",
                                   model_size,
                                   " and the row-sparse backward scatters "
                                   "only touched rows (docs/SPARSE.md)"
                                   if op == "SparseEmbedding" else ""))
                    else:
                        hint = ("parallel.sharding.param_pspec would shard "
                                "it — drop the custom param_rule for this "
                                "name or return its spec")
                    diags.append(Diagnostic(
                        "GL405",
                        "parameter %r %s (%s) is replicated on every device "
                        "although dim %d divides the model axis (%s-way)"
                        % (node.name, tuple(shape),
                           fmt_bytes(elems * _itemsize(
                               ctx.var_dtype.get(node.name))),
                           next(d for d, a in enumerate(default) if a),
                           model_size),
                        node=node.name,
                        fix_hint=hint,
                    ))
                elif (len(shape) == 2 and elems >= MIN_SHARD_ELEMS
                      and not shardable_dims(shape, model_size)):
                    diags.append(Diagnostic(
                        "GL401",
                        "parameter %r %s (%s) was requested sharded over the "
                        "model axis (%d-way) but neither dim divides — the "
                        "rule silently fell back to FULL replication on all "
                        "%d devices"
                        % (node.name, tuple(shape),
                           fmt_bytes(elems * _itemsize(
                               ctx.var_dtype.get(node.name))),
                           model_size, mesh.size),
                        node=node.name,
                        fix_hint="pad the layer width to a multiple of %d "
                                 "(or pick a divisible num_hidden) so "
                                 "param_pspec can split it" % model_size,
                    ))
        ctx.entry_spec[(id(node), 0)] = spec

    # ---- propagate through op nodes, collecting reshard edges -----------
    edges = []  # (node, input_node, dims, why, factor, spec_str, bytes_moved)
    heads = {id(n) for n, _ in ctx.symbol._outputs}
    for node in ctx.topo:
        if node.is_variable:
            continue
        try:
            parsed = node.parsed_attrs()
        except Exception:
            parsed = {}
        meta = get_meta(node.op)
        in_specs = [ctx.entry_spec.get((id(inp), oi))
                    for inp, oi in node.inputs]
        in_shapes = [ctx.entry_shape.get((id(inp), oi))
                     for inp, oi in node.inputs]
        out_shapes = [ctx.entry_shape.get((id(node), i))
                      for i in range(node.num_outputs())]
        out_specs, gathers = _propagate_node(node, parsed, meta, in_specs,
                                             in_shapes, out_shapes)
        for i, sh, sp in zip(range(node.num_outputs()), out_shapes, out_specs):
            ctx.entry_spec[(id(node), i)] = (
                sp if sp is not None else _replicated(len(sh or ())))
        for i, dims, why in gathers:
            inp, oi = node.inputs[i]
            sh = in_shapes[i]
            sp = in_specs[i]
            if sh is None or sp is None:
                continue
            f = 1
            for d in dims:
                f *= spec_factor(sp, mesh, dim=d)
            if f <= 1:
                continue
            if meta.shard_rule in ("embedding", "row_sparse_embedding") \
                    and i == 1:
                # a vocab-sharded table never moves: the masked-sum psum
                # traffic is the LOOKUP OUTPUT, once per non-owner shard
                osh = out_shapes[0]
                if osh is None:
                    continue
                total = int(np.prod(osh)) * _itemsize(
                    ctx.entry_dtype.get((id(node), 0)))
            else:
                total = int(np.prod(sh)) * _itemsize(
                    ctx.entry_dtype.get((id(inp), oi)))
            moved = total * (f - 1) // f
            edges.append((node, inp, dims, why, f, _spec_str(sp), moved))

        # ---- GL403: the data axis vanished mid-graph --------------------
        dax = rules.data_axis
        if dax is not None:
            in_has = any(dax in a for sp in in_specs if sp for a in sp)
            out_has = any(dax in a for sp in out_specs if sp for a in sp)
            if in_has and not out_has and id(node) not in heads:
                big_bytes = max(
                    (int(np.prod(sh)) * _itemsize(
                        ctx.entry_dtype.get((id(inp), oi)))
                     for (inp, oi), sh in zip(node.inputs, in_shapes)
                     if sh is not None),
                    default=None)
                diags.append(Diagnostic(
                    "GL403",
                    "%s (%s) collapses the %r-sharded batch dim mid-graph: "
                    "its output is replicated, so every consumer downstream "
                    "runs un-sharded and the op itself gathers %s of "
                    "activations"
                    % (node.name, node.op, dax,
                       fmt_bytes(big_bytes) if big_bytes is not None
                       else "its inputs"),
                    node=node.name, op=node.op,
                    provenance=ctx.provenance(node, depth=2, max_lines=4),
                    fix_hint="keep a batch dim through this op (keepdims=1 "
                             "/ reshape around it) or move the reduction "
                             "into the loss head",
                ))

    # ---- GL402: per-edge reshard diagnostics (largest first, capped) -----
    edges.sort(key=lambda e: -e[-1])
    # Machine-readable, UNCAPPED view for the auto-parallel planner and JSON
    # consumers: the human diagnostics below stay capped at _EDGE_CAP, but a
    # cost model fed a truncated total would under-price bad plans.
    ctx.reshard_total_bytes = int(sum(m for *_, m in edges))
    ctx.reshard_edges = [
        {"consumer": node.name, "op": node.op, "producer": inp.name,
         "dims": list(dims), "factor": int(f), "spec": spec_str,
         "bytes_per_device": int(moved)}
        for node, inp, dims, why, f, spec_str, moved in edges]
    for node, inp, dims, why, f, spec_str, moved in edges[:_EDGE_CAP]:
        diags.append(Diagnostic(
            "GL402",
            "implicit reshard into %s (%s): input %r dim(s) %s are sharded "
            "%d-way but %s — est %s moved per device (all-gather of %s)"
            % (node.name, node.op, inp.name, list(dims), f, why,
               fmt_bytes(moved), spec_str),
            node=node.name, op=node.op,
            fix_hint="make the producer and consumer agree on this layout "
                     "(shard the consumer's other operand to match, or "
                     "replicate the producer)",
        ))
    if len(edges) > _EDGE_CAP:
        rest = edges[_EDGE_CAP:]
        tail = ["%s -> %s (%s): %s" % (inp.name, node.name, node.op,
                                       fmt_bytes(moved))
                for node, inp, _, _, _, _, moved in rest[:_SUMMARY_CAP]]
        if len(rest) > _SUMMARY_CAP:
            tail.append("and %d more" % (len(rest) - _SUMMARY_CAP))
        diags.append(Diagnostic(
            "GL402",
            "%d smaller implicit reshard edge(s), est %s total moved per "
            "device" % (len(rest), fmt_bytes(sum(m for *_, m in rest))),
            node=rest[0][0].name,
            provenance=tail,
        ))

    # ---- GL404: uneven shards over every placed entry --------------------
    uneven = []
    for node in ctx.topo:
        for i in range(node.num_outputs()):
            sp = ctx.entry_spec.get((id(node), i))
            sh = ctx.entry_shape.get((id(node), i))
            if not sp or sh is None:
                continue
            for d, axes in enumerate(sp):
                if not axes:
                    continue
                f = spec_factor(sp, mesh, dim=d)
                if f > 1 and sh[d] % f:
                    uneven.append((node, d, sh, f))
    for node, d, sh, f in uneven[:_EDGE_CAP]:
        pad = (-sh[d]) % f
        diags.append(Diagnostic(
            "GL404",
            "%s: dim %d extent %d does not divide its %d-way sharding — "
            "XLA pads every shard to %d row(s) (%d padded row(s) in total "
            "across the axis, dead compute+HBM)"
            % (ctx.node_label(node), d, sh[d], f, -(-sh[d] // f), pad),
            node=node.name,
            fix_hint="pad the batch/layer to a multiple of %d, or shrink "
                     "the mesh axis" % f,
        ))
    if len(uneven) > _EDGE_CAP:
        rest = uneven[_EDGE_CAP:]
        tail = ["%s dim %d extent %d %% %d" % (ctx.node_label(node), d,
                                               sh[d], f)
                for node, d, sh, f in rest[:_SUMMARY_CAP]]
        if len(rest) > _SUMMARY_CAP:
            tail.append("and %d more" % (len(rest) - _SUMMARY_CAP))
        diags.append(Diagnostic(
            "GL404",
            "%d more tensor(s) with uneven shards" % len(rest),
            node=rest[0][0].name,
            provenance=tail,
        ))
    return diags
