"""Static memory-liveness / peak-HBM planner (GL501–GL5xx).

The reference framework planned buffers at graph level (nnvm PlanMemory:
liveness over the topo order, reference-counted frees, one arena). XLA owns
real allocation now — but it tells you the verdict only after minutes of
compilation, as an OOM. This pass re-derives the *prediction* from the
Symbol DAG alone, per device under the sharding plan:

  * params + gradients + optimizer state (momentum-class, one slot per
    param) + the live-activation watermark, forward AND backward,
  * activation bytes counted per entry under ``ctx.entry_spec`` (the
    GL4xx propagation) — a dp=8 plan holds 1/8th of every batch-sharded
    activation per device,
  * a stash-vs-recompute toggle in the ``ops/conv_bn_bytes.py`` accounting
    style: ``stash`` keeps every op output across the fwd→bwd transition
    (the no-remat executor default); ``recompute`` keeps only MXU-op
    outputs (conv/FC/dot/embedding — the ``remat='dots'`` policy) and
    charges the recomputed operands transiently during each backward node.

Findings:
  GL501  predicted peak exceeds ``MXNET_MEMLINT_BUDGET_GB`` (or the
         caller's ``budget_gb``) — named peak node + its live tensors
  GL502  one activation alone is ≥ half the live-activation watermark
         (and over an absolute floor) — the recompute/stash pointer

The full table (clean graphs included) lands on ``Report.memory_plan`` and,
when telemetry is enabled, the ``memlint.predicted_peak_bytes`` gauge — so
``mxtrace`` can show predicted vs. actual side by side.
"""
from __future__ import annotations

from .diagnostics import Diagnostic
from .manager import GraphContext, graph_pass
from .shard_lint import batch_like_vars, entry_bytes, fmt_bytes, norm_spec

__all__ = ["plan_memory", "memory_plan_lint", "DOMINANT_FLOOR_BYTES"]

# ops whose outputs the 'recompute' policy keeps across fwd→bwd (the
# jax.checkpoint 'dots_with_no_batch_dims_saveable' family: MXU results are
# kept, cheap elementwise/norm chains are re-derived in backward)
_MXU_OPS = frozenset({"Convolution", "Deconvolution", "FullyConnected",
                      "dot", "batch_dot", "Embedding", "RNN"})

# GL502 floor: below this a "dominant" activation is not worth a finding
DOMINANT_FLOOR_BYTES = 1 << 30  # 1 GiB

# the fused attention op: its dense lowering's autodiff stashes the
# (B, H, T, S) softmax probabilities across fwd→bwd — an OP-INTERNAL
# residual no graph entry carries, modeled explicitly below (and elided
# when the flash training path will engage: the online-softmax recompute
# backward keeps only the (B, H, T, 1) logsumexp)
_ATTN_OPS = frozenset({"_contrib_MultiHeadAttention", "MultiHeadAttention"})

_TOP_LIVE = 8  # live tensors named at the peak


def _entry_label(ctx, node, oi):
    name = ctx.node_label(node)
    if node.num_outputs() > 1:
        name += "[%d]" % oi
    return name


def plan_memory(ctx: GraphContext):
    """Liveness walk over the topo-sorted DAG. Returns the plan dict, or
    None when the graph's shapes are not fully determined (structural lint —
    there is nothing finite to predict)."""
    from ..parallel.mesh import MeshSpec

    mesh = MeshSpec.of(ctx.mesh) if ctx.mesh is not None else None

    class _M:  # replicated fallback mesh for the byte helper
        shape = {}

    m = mesh if mesh is not None else _M()

    op_nodes = [n for n in ctx.topo if not n.is_variable]
    entries = []
    for node in op_nodes:
        entries.extend((node, i) for i in range(node.num_outputs()))

    def ebytes(node, oi):
        sh = ctx.entry_shape.get((id(node), oi))
        if sh is None:
            return None
        spec = ctx.entry_spec.get((id(node), oi)) or norm_spec(None, len(sh))
        return entry_bytes(sh, ctx.entry_dtype.get((id(node), oi)), spec, m)

    sizes = {}
    for node, oi in entries:
        b = ebytes(node, oi)
        if b is None:
            return None  # underdetermined graph: no finite prediction
        sizes[(id(node), oi)] = b

    # ---- static components ----------------------------------------------
    data_like = {n.name for n in batch_like_vars(ctx)}
    params = grads = inputs = 0
    aux_ids = {id(n) for n in ctx.aux_nodes}
    for node in ctx.arg_nodes + ctx.aux_nodes:
        b = ebytes(node, 0)
        if b is None:
            return None
        if node.name in data_like:
            inputs += b
        else:
            params += b
            # aux (BN running stats) carry no grad/optimizer state
            if ctx.train and id(node) not in aux_ids:
                grads += b
    opt = grads if ctx.train else 0  # one momentum-class slot per param
    base = params + grads + opt + inputs

    # ---- forward liveness -----------------------------------------------
    order = {id(n): i for i, n in enumerate(op_nodes)}
    heads = {(id(n), oi) for n, oi in ctx.symbol._outputs}
    remaining = {}  # entry -> #consumers not yet executed (forward)
    for node in op_nodes:
        for inp, oi in node.inputs:
            if not inp.is_variable:
                remaining[(id(inp), oi)] = remaining.get((id(inp), oi), 0) + 1

    stash_all = ctx.train and ctx.bwd_policy == "stash"
    stashed = set()
    if ctx.train:
        for node, oi in entries:
            if stash_all or node.op in _MXU_OPS:
                stashed.add((id(node), oi))

    # attention score-stash model: the dense lowering's backward needs the
    # f32 (B, H, T, S) probabilities, held from the op's forward to its
    # backward — charged per site unless the flash training path engages
    # for that exact (shape, dtype) site (fusion.attention_trains_flash)
    attn_stash, attn_info = {}, None
    if ctx.train:
        attn_info = {"sites": 0, "score_bytes": 0, "flash_elided_sites": 0}
        for node in op_nodes:
            if node.op not in _ATTN_OPS or not node.inputs:
                continue
            attn_info["sites"] += 1
            q_n, q_oi = node.inputs[0]
            k_n, k_oi = node.inputs[1] if len(node.inputs) > 1 else (None, 0)
            q_sh = ctx.entry_shape.get((id(q_n), q_oi))
            k_sh = ctx.entry_shape.get((id(k_n), k_oi)) if k_n is not None \
                else None
            if not q_sh or not k_sh or len(q_sh) != 4 or len(k_sh) != 4:
                continue
            a = node.parsed_attrs()
            try:
                from .. import fusion as _fusion

                flash = _fusion.attention_trains_flash(
                    q_sh, k_sh, ctx.entry_dtype.get((id(node), 0))
                    or "float32", a.get("causal"), a.get("scale", -1.0))
            except Exception:
                flash = False
            if flash:
                attn_info["flash_elided_sites"] += 1
                continue
            out_spec = norm_spec(ctx.entry_spec.get((id(node), 0)), 4)
            score_shape = (q_sh[0], q_sh[1], q_sh[2], k_sh[2])
            b = entry_bytes(score_shape, "float32",
                            tuple(out_spec[:3]) + ((),), m)
            attn_stash[id(node)] = b
            attn_info["score_bytes"] += int(b)
        if not attn_info["sites"]:
            attn_info = None

    live = {}  # entry -> bytes
    peak = -1
    peak_node, peak_phase, peak_live = None, "forward", []

    def note_peak(node, phase):
        nonlocal peak, peak_node, peak_phase, peak_live
        cur = sum(live.values())
        if cur > peak:
            peak = cur
            peak_node = node.name
            peak_phase = phase
            rows = sorted(live.items(), key=lambda kv: -kv[1])[:_TOP_LIVE]
            peak_live = [(lbl.get(k, "?"), v) for k, v in rows]

    lbl = {"__cotangents__": "<cotangents>",
           "__recompute__": "<recomputed operands>"}
    for node, oi in entries:
        lbl[(id(node), oi)] = _entry_label(ctx, node, oi)
    for node in op_nodes:
        if id(node) in attn_stash:
            lbl[("__attn_scores__", id(node))] = \
                ctx.node_label(node) + "<scores>"

    for node in op_nodes:
        for i in range(node.num_outputs()):
            live[(id(node), i)] = sizes[(id(node), i)]
        if id(node) in attn_stash:
            live[("__attn_scores__", id(node))] = attn_stash[id(node)]
        note_peak(node, "forward")
        for inp, oi in node.inputs:
            e = (id(inp), oi)
            if inp.is_variable or e not in remaining:
                continue
            remaining[e] -= 1
            if (remaining[e] == 0 and e not in heads
                    and not (ctx.train and e in stashed)):
                live.pop(e, None)
        # an output nobody consumes: keep if head, else free non-stashed
        for i in range(node.num_outputs()):
            e = (id(node), i)
            if (e not in heads and remaining.get(e, 0) == 0
                    and not (ctx.train and e in stashed)):
                live.pop(e, None)

    # ---- backward liveness ----------------------------------------------
    if ctx.train:
        # cotangent of entry e: born at e's first consumer's backward (or at
        # the head), dies after e's producer's backward consumes it
        cot = {}
        for node, oi in ctx.symbol._outputs:
            if not node.is_variable:
                cot[(id(node), oi)] = sizes.get((id(node), oi), 0)
        for node in reversed(op_nodes):
            # grads flowing to this node's inputs materialize now
            for inp, oi in node.inputs:
                e = (id(inp), oi)
                if not inp.is_variable and e not in cot and e in sizes:
                    cot[e] = sizes[e]
            # recompute policy: un-stashed operands rematerialize for this
            # node's backward — transiently resident
            extra = 0
            for inp, oi in node.inputs:
                e = (id(inp), oi)
                if (not inp.is_variable and e not in stashed
                        and e not in live and e in sizes):
                    extra += sizes[e]
            live["__recompute__"] = extra
            live["__cotangents__"] = sum(cot.values())
            note_peak(node, "backward")
            live.pop("__recompute__", None)
            # this node's backward ran: its output cotangents, stashed
            # outputs and internal score stash are dead
            for i in range(node.num_outputs()):
                cot.pop((id(node), i), None)
                e = (id(node), i)
                if e not in heads:
                    live.pop(e, None)
            live.pop(("__attn_scores__", id(node)), None)
        live.pop("__cotangents__", None)

    act_peak = max(peak, 0)
    total = base + act_peak
    fusion_info = _fusion_byte_view(ctx, op_nodes, sizes, stash_all)
    plan = {
        "per_device": {
            "params": int(params),
            "grads": int(grads),
            "opt_state": int(opt),
            "inputs": int(inputs),
            "act_peak": int(act_peak),
            "peak": int(total),
        },
        "peak_gb": round(total / 2 ** 30, 4),
        "peak_node": peak_node,
        "peak_phase": peak_phase,
        "peak_live": [[n, int(b)] for n, b in peak_live],
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "devices": mesh.size if mesh is not None else 1,
        "policy": ctx.bwd_policy if ctx.train else "inference",
        "train": ctx.train,
        "budget_bytes": (int(ctx.budget_bytes)
                         if ctx.budget_bytes is not None else None),
    }
    if fusion_info is not None:
        plan["fusion"] = fusion_info
    if attn_info is not None:
        plan["attention"] = attn_info
    return plan


def _fusion_byte_view(ctx, op_nodes, sizes, stash_all):
    """The fusion pattern engine's byte view of this graph: per-pattern
    site counts and the interior (pattern-elided) bytes — activations that
    never materialize when their site engages. Under the ``stash`` policy
    those interiors would otherwise be HELD across the fwd→bwd transition,
    so ``stash_elidable_bytes`` is the stash-watermark headroom (in bytes)
    the engine can unlock there (0 under recompute/inference, where the
    interiors are transient anyway); the prediction above stays the
    conservative (unfused) upper bound. None when no pattern roots in
    this graph."""
    try:
        from .. import fusion

        directives = fusion.plan(
            ctx.topo, output_ids={id(n) for n, _ in ctx.symbol._outputs})
        sites, interior = {}, 0
        for node in op_nodes:
            d = directives.get(id(node))
            if d is None:
                continue
            if d["kind"] == "pattern":
                sites[d["pat"].name] = sites.get(d["pat"].name, 0) + 1
            elif d["kind"] == "lazy":
                interior += sizes.get((id(node), 0), 0)
        if not sites:
            return None
        return {"pattern_sites": sites,
                "interior_bytes": int(interior),
                "stash_elidable_bytes":
                    int(interior) if (ctx.train and stash_all) else 0}
    except Exception:  # the refinement must never sink the prediction
        return None


@graph_pass("memory_plan")
def memory_plan_lint(ctx: GraphContext):
    plan = plan_memory(ctx)
    ctx.memory_plan = plan
    if plan is None:
        return []

    from .. import telemetry as _tm

    if _tm.enabled():
        _tm.gauge("memlint.predicted_peak_bytes").set(
            plan["per_device"]["peak"])

    diags = []
    pd = plan["per_device"]
    if ctx.budget_bytes is not None and pd["peak"] > ctx.budget_bytes:
        comp = max(("params", "grads", "opt_state", "act_peak"),
                   key=lambda k: pd[k])
        hints = {
            "params": "shard more params over the model axis "
                      "(parallel.sharding.param_pspec) or grow the mesh",
            "grads": "shard params (grads follow their layout) or grow the "
                     "data axis",
            "opt_state": "shard params or use a stateless optimizer",
            "act_peak": "switch the backward policy to recompute "
                        "(SPMDTrainer(remat='dots')) or shrink the "
                        "per-device batch",
        }
        diags.append(Diagnostic(
            "GL501",
            "predicted peak HBM %s/device exceeds the %s budget "
            "(params %s + grads %s + opt %s + inputs %s + activations %s); "
            "peak at %s (%s) with %s live"
            % (fmt_bytes(pd["peak"]), fmt_bytes(int(ctx.budget_bytes)),
               fmt_bytes(pd["params"]), fmt_bytes(pd["grads"]),
               fmt_bytes(pd["opt_state"]), fmt_bytes(pd["inputs"]),
               fmt_bytes(pd["act_peak"]),
               plan["peak_node"], plan["peak_phase"],
               ", ".join("%s=%s" % (n, fmt_bytes(b))
                         for n, b in plan["peak_live"][:4]) or "nothing"),
            node=plan["peak_node"],
            fix_hint="%s component dominates: %s — or let the auto-parallel "
                     "planner search dp×tp×pp plans under this "
                     "budget for you: MXNET_AUTOPLAN=1 (trainer) / "
                     "graphlint --autoplan (CLI)" % (comp, hints[comp]),
        ))
    # the largest single ACTIVATION at the peak (the synthetic
    # <cotangents>/<recomputed> lumps are not one tensor a policy can fix)
    top = next(((n, b) for n, b in plan["peak_live"]
                if not n.startswith("<")), None)
    if top is not None:
        top_name, top_bytes = top
        if (top_bytes >= DOMINANT_FLOOR_BYTES
                and pd["act_peak"] > 0
                and top_bytes * 2 >= pd["act_peak"]):
            diags.append(Diagnostic(
                "GL502",
                "one activation (%s, %s) is %d%% of the live-activation "
                "watermark at the %s peak"
                % (top_name, fmt_bytes(top_bytes),
                   100 * top_bytes // pd["act_peak"], plan["peak_phase"]),
                node=plan["peak_node"],
                fix_hint="recompute it in backward instead of stashing "
                         "(bwd policy 'recompute' / SPMDTrainer("
                         "remat='dots')), or shard the dim it is largest in",
            ))
    return diags
