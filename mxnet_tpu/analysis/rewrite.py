"""Symbol→Symbol graph-rewrite pass framework (GL6xx provenance contract).

Every pass in this package used to be read-only: six GLxxx families
diagnose the Symbol DAG, nothing improves it, so the graph handed to the
fusion engine and the auto-parallel planner is as sloppy as the frontend
wrote it. Relay's thesis (PAPERS.md) is that framework-level rewrites —
constant folding, CSE, DCE, dtype legalization — compose with and amplify
downstream fusion; the XLA operator-fusion study quantifies what is left
on the table when the compiler receives an unoptimized graph. This module
is the write side: a pass manager that rewrites a Symbol into an
equivalent, cleaner Symbol at bind time, with every change provenance-
tracked and statically verifiable.

Passes (run to fixpoint, ``MXNET_GRAPHREWRITE_ROUNDS`` budget):

* ``const_fold``   — subgraphs whose leaves are all init ops (``_zeros``,
  ``_arange``, ...) evaluate ONCE host-side into a ``_graph_const`` node;
  the executor then ships a literal instead of recomputing the subgraph
  every step.
* ``cse``          — common-subexpression elimination over a canonical
  node-signature hash ``(op, frozen attrs, input entries)``; stateful ops
  (aux, rng) and program-output nodes never merge.
* ``canonicalize`` — normalizes computationally-identical spellings into
  the forms ``ops/fusion_patterns.py`` matchers expect (``x*x`` →
  ``square``, positive reduction axes → negative, bare ``relu`` →
  ``Activation``, ``1/sqrt`` → ``rsqrt``, scalar-identity/_copy elision)
  so ``norm_residual``/``elemwise_chain``/``matmul_bias_act`` root more
  sites. Every rule is bitwise-preserving on the XLA lowering (tested).
* ``bf16``         — dtype legalization (opt-in,
  ``MXNET_GRAPHREWRITE_BF16=1``): cast-sandwiches the MXU-bound operands
  declared in ``ops/infer_meta.py`` ``bf16_slots`` (f32 in → bf16 compute
  → f32 out), leaving every downstream dtype unchanged.
* ``dce``          — sweeps nodes the other passes orphaned (and anything
  unreachable from the outputs), counting what died.

Every firing emits a provenance record ``{pass, rule, action, node,
origins}``; ``verify_rewrite`` checks the records statically — the GL6xx
family:

  GL601  rewrite changed an output's inferred shape/dtype (error)
  GL602  provenance gap: a created node no rule claims (error)
  GL603  fixpoint not reached within the round budget (warn)
  GL604  rewrite-eliminated argument still referenced by a grad_req (error)
  GL605  summary: nodes folded/merged/removed + bytes-saved estimate (info)

Gate: ``MXNET_GRAPHREWRITE=0|on|verify`` (default ``0``). ``on`` rewrites
at ``executor.bind``/``simple_bind`` and on the ``SPMDStepAdapter`` fused
path; ``verify`` additionally runs the GL6xx verifier per bind and raises
on any error-severity finding. Telemetry: ``rewrite.runs``,
``rewrite.nodes_folded/merged/removed``, ``rewrite.casts_inserted``,
``rewrite.fallbacks`` counters and a ``rewrite.pass`` span per pass.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError, np_dtype
from ..ops.infer_meta import get_meta
from ..symbol import Symbol, _Node, _freeze, _topo_order
from .diagnostics import Diagnostic, Report
from .. import telemetry as _tm

__all__ = ["rewrite", "verify_rewrite", "graphrewrite_mode", "RewritePass",
           "RewriteResult", "rewrite_pass_names", "pattern_site_counts"]

_LOG = logging.getLogger("mxnet_tpu.graphrewrite")

#: constant-fold result cap: a folded literal larger than this stays
#: unfolded (embedding a huge array into the graph would trade a cheap
#: recompute for resident HBM + trace bloat)
_FOLD_CAP_BYTES = 64 << 20


# --------------------------------------------------------------------- env
_warned_modes = set()


def graphrewrite_mode() -> Optional[str]:
    """The ``MXNET_GRAPHREWRITE`` knob: ``None`` (off, the default),
    ``"on"`` (rewrite at bind), or ``"verify"`` (rewrite + GL6xx verifier
    per bind, raising on GL601/GL602/GL604). Boolean-style truthy values
    mean ``on``; anything unrecognized warns once and stays off."""
    raw = os.environ.get("MXNET_GRAPHREWRITE", "0").strip().lower()
    if raw == "verify":
        return "verify"
    if raw in ("on", "1", "true"):
        return "on"
    if raw not in ("", "0", "false", "off") and raw not in _warned_modes:
        _warned_modes.add(raw)
        _LOG.warning("MXNET_GRAPHREWRITE=%r is not a recognized mode "
                     "(0|on|verify); graph rewrites stay OFF", raw)
    return None


def _bf16_enabled() -> bool:
    return os.environ.get("MXNET_GRAPHREWRITE_BF16", "0").strip() == "1"


def _max_rounds() -> int:
    raw = os.environ.get("MXNET_GRAPHREWRITE_ROUNDS", "").strip()
    try:
        v = int(raw) if raw else 4
        return v if v > 0 else 4
    except ValueError:
        return 4


# ------------------------------------------------------------ working graph
class _RGraph:
    """The mutable working copy one rewrite pipeline operates on.

    Cloned from the input Symbol so rewrites never touch the caller's
    graph. Tracks every pass-created node (``created``) and every
    provenance record (``records``); ``live`` is the node set as of the
    last DCE sweep — the delta against fresh reachability is what DCE
    counts."""

    def __init__(self, symbol: Symbol, shapes=None, types=None):
        mapping = {}
        for node in symbol._topo():
            clone = _Node(node.op, node.name, dict(node.attrs),
                          [(mapping[id(i)], oi) for i, oi in node.inputs])
            mapping[id(node)] = clone
        self.outputs: List[Tuple[_Node, int]] = [
            (mapping[id(n)], oi) for n, oi in symbol._outputs]
        self.shapes = dict(shapes or {})
        self.types = dict(types or {})
        self.records: List[dict] = []
        self.created: Dict[int, _Node] = {}
        self.live: List[_Node] = self.topo()
        self.counts = {"folded": 0, "merged": 0, "removed": 0, "casts": 0}
        self._infer_cache = None

    # ---------------------------------------------------------- structure
    def _heads(self):
        seen, heads = set(), []
        for node, _ in self.outputs:
            if id(node) not in seen:
                seen.add(id(node))
                heads.append(node)
        return heads

    def topo(self) -> List[_Node]:
        return _topo_order(self._heads())

    def output_ids(self):
        return {id(n) for n, _ in self.outputs}

    def symbol(self) -> Symbol:
        return Symbol(list(self.outputs))

    def invalidate(self):
        self._infer_cache = None

    def infer(self):
        """(entry_shape, entry_dtype) tables for the CURRENT graph, via the
        lint propagation pass (per-node error recovery: an uninferrable
        node just reads None). Cached until ``invalidate()``."""
        if self._infer_cache is None:
            from .manager import GraphContext
            from .shape_lint import propagate

            ctx = GraphContext(self.symbol(), shape_hints=self.shapes,
                               type_hints=self.types, strict_shapes=False)
            propagate(ctx)
            self._infer_cache = (ctx.entry_shape, ctx.entry_dtype)
        return self._infer_cache

    # ------------------------------------------------------------- editing
    def new_node(self, op, name, attrs, inputs) -> _Node:
        node = _Node(op, name, dict(attrs or {}), list(inputs))
        self.created[id(node)] = node
        return node

    def apply_entry_map(self, entry_map, skip_nodes=()):
        """Rewire every input edge and output head through ``entry_map``
        ({(id(old), oi): (new_node, new_oi)}), following chains. Nodes in
        ``skip_nodes`` keep their inputs verbatim (a cast inserted AFTER a
        node must keep reading that node, not itself)."""
        if not entry_map:
            return

        def resolve(entry):
            seen = set()
            while (id(entry[0]), entry[1]) in entry_map:
                key = (id(entry[0]), entry[1])
                if key in seen:  # defensive: a cyclic map would hang
                    break
                seen.add(key)
                entry = entry_map[key]
            return entry

        skip = {id(n) for n in skip_nodes}
        # walk the reachable set PLUS every pass-created node: a node
        # created mid-pass (e.g. an Activation replacing a relu) copied its
        # inputs before the map existed and is not yet reachable from the
        # outputs — missing it would leave stale edges into replaced nodes
        # (phantom records, double firings, extra fixpoint rounds)
        nodes = {id(n): n for n in self.topo()}
        for n in self.created.values():
            nodes.setdefault(id(n), n)
        for node in nodes.values():
            if id(node) in skip:
                continue
            node.inputs = [resolve(e) for e in node.inputs]
        self.outputs = [resolve(e) for e in self.outputs]
        self.invalidate()

    def note(self, pass_name, rule, action, node=None, origins=(), **extra):
        rec = {"pass": pass_name, "rule": rule, "action": action,
               "node": node, "origins": list(origins)}
        rec.update(extra)
        self.records.append(rec)


class RewritePass:
    """One rewrite pass: ``run(g)`` mutates the working graph and returns
    the number of rule firings (0 = nothing to do, the fixpoint signal).
    Built-in passes live below; tests may hand ``rewrite(passes=[...])``
    custom instances to exercise the verifier."""

    name = "<unnamed>"

    def run(self, g: _RGraph) -> int:  # pragma: no cover - interface
        raise NotImplementedError


# ------------------------------------------------------------- const_fold
def _is_pure(opdef):
    return (not opdef.needs_rng and not opdef.has_aux
            and not opdef.needs_train_flag)


class ConstFoldPass(RewritePass):
    """Evaluate init-op-only subgraphs once, host-side.

    A node is *const* when it is an op node, pure (no rng/aux/train flag),
    and every input is const — the induction grounds out at the zero-input
    init ops (``_zeros``/``_ones``/``_full``/``_arange``). Variables are
    NEVER const: args and aux states are runtime values (folding a
    moving-stat-fed subgraph would freeze training statistics). The fold
    frontier — a const node with a non-const consumer or a program output
    — becomes one ``_graph_const`` literal; the upstream const chain is
    swept by DCE."""

    name = "const_fold"

    def run(self, g: _RGraph) -> int:
        topo = g.topo()
        const: Dict[int, bool] = {}
        consumers: Dict[int, list] = {}
        for node in topo:
            for inp, oi in node.inputs:
                consumers.setdefault(id(inp), []).append(node)
        for node in topo:
            if node.is_variable or node.op == "_graph_const":
                const[id(node)] = False
                continue
            try:
                opdef = node.opdef()
            except MXNetError:
                const[id(node)] = False
                continue
            const[id(node)] = (_is_pure(opdef)
                               and all(const[id(i)] for i, _ in node.inputs))
        out_ids = g.output_ids()
        vals: Dict[Tuple[int, int], np.ndarray] = {}

        def value(entry):
            node, oi = entry
            key = (id(node), oi)
            if key not in vals:
                ins = [value(e) for e in node.inputs]
                outs, _ = node.opdef().apply(node.parsed_attrs(), ins,
                                             aux=[], is_train=False,
                                             rng=None)
                for i, o in enumerate(outs):
                    vals[(id(node), i)] = np.asarray(o)
            return vals[key]

        entry_map, fired = {}, 0
        for node in topo:
            if not const[id(node)] or not node.inputs:
                continue  # a bare init op is already a single leaf
            if node.num_outputs() != 1:
                continue
            boundary = (id(node) in out_ids
                        or any(not const[id(c)]
                               for c in consumers.get(id(node), [])))
            if not boundary:
                continue  # an interior const node folds into its consumer
            try:
                arr = value((node, 0))
            except Exception as exc:  # a fold failure must never sink a bind
                _LOG.warning("const_fold: evaluating %r failed (%s); left "
                             "unfolded", node.name, exc)
                continue
            if arr.nbytes > _FOLD_CAP_BYTES:
                continue
            # the literal takes the folded node's NAME: the old node is
            # swept (no collision) and a program-output entry keeps its
            # output name — outputs must bind exactly where they did
            lit = g.new_node(
                "_graph_const", node.name,
                {"data": arr.tobytes(), "shape": tuple(arr.shape),
                 "dtype": arr.dtype.name}, [])
            entry_map[(id(node), 0)] = (lit, 0)
            g.note(self.name, "fold", "fold", node=lit.name,
                   origins=[node.name])
            g.counts["folded"] += 1
            fired += 1
        g.apply_entry_map(entry_map)
        return fired


# -------------------------------------------------------------------- cse
class CSEPass(RewritePass):
    """Merge op nodes with identical canonical signatures
    ``(op, frozen attrs, input entries)``. One topo walk with incremental
    rewiring, so chains of duplicates (dup mean → dup center) collapse in
    a single pass. Stateful ops (aux, rng) never merge — two Dropouts are
    two masks, two BatchNorms are two moving-stat updates. A node whose
    value is a program output keeps its identity (merging it away would
    rename the output)."""

    name = "cse"

    def run(self, g: _RGraph) -> int:
        canon: Dict[tuple, _Node] = {}
        entry_map, fired = {}, 0
        out_ids = g.output_ids()

        def resolve(entry):
            while (id(entry[0]), entry[1]) in entry_map:
                entry = entry_map[(id(entry[0]), entry[1])]
            return entry

        for node in g.topo():
            node.inputs = [resolve(e) for e in node.inputs]
            if node.is_variable:
                continue
            try:
                opdef = node.opdef()
            except MXNetError:
                continue
            if opdef.needs_rng or opdef.has_aux:
                continue
            if node.op == "_graph_const":
                # each folded literal is identity-unique; freezing+hashing
                # its raw byte payload (up to the 64 MB fold cap) per CSE
                # round would dominate bind time for nothing
                continue
            try:
                key = (node.op, _freeze(node.parsed_attrs()),
                       tuple((id(i), oi) for i, oi in node.inputs))
                hash(key)
            except Exception:
                continue  # unhashable attr payloads opt the node out
            prev = canon.get(key)
            if prev is None:
                canon[key] = node
            elif id(node) not in out_ids:
                for i in range(node.num_outputs()):
                    entry_map[(id(node), i)] = (prev, i)
                g.note(self.name, "merge", "merge", node=prev.name,
                       origins=[node.name])
                g.counts["merged"] += 1
                fired += 1
        g.apply_entry_map(entry_map)
        return fired


# ----------------------------------------------------------- canonicalize
def _same_entry(a, b):
    return a[0] is b[0] and a[1] == b[1]


class CanonicalizePass(RewritePass):
    """Normalize computationally-identical spellings into the canonical
    forms the fusion-pattern matchers (``ops/fusion_patterns.py``) and the
    other analysis passes expect. Every rule is bitwise-preserving on the
    XLA lowering (``tests/test_graph_rewrite.py`` pins this per rule):

    * ``mul_self_to_square``  — ``elemwise_mul(x, x)`` / ``broadcast_mul``
      of one entry with itself → ``square(x)``.
    * ``negative_axis``       — positive reduction axes on ``mean``/``sum``
      (known rank) → the negative canonical form ``norm_residual`` keys on.
    * ``relu_to_activation``  — the bare ``relu`` op → ``Activation
      (act_type=relu)``, the spelling ``matmul_bias_act`` roots.
    * ``rsqrt_compose``       — ``reciprocal(sqrt(x))`` and ``1/sqrt(x)``
      (``_rdiv_scalar`` scalar=1) → ``rsqrt(x)``.
    * ``identity_elide``      — ``_mul_scalar/_div_scalar`` by 1.0 and
      ``_copy`` vanish (``_plus_scalar`` 0.0 is deliberately NOT elided:
      ``-0.0 + 0.0`` flips the sign bit).
    """

    name = "canonicalize"

    _REDUCES = ("mean", "sum", "sum_axis", "max", "max_axis", "min",
                "min_axis", "prod", "nansum", "nanprod")

    def run(self, g: _RGraph) -> int:
        entry_map, fired = {}, 0
        out_ids = g.output_ids()
        shapes, dtypes = g.infer()

        for node in g.topo():
            if node.is_variable:
                continue
            try:
                parsed = node.parsed_attrs()
            except Exception:
                continue

            # mul(x, x) -> square(x)
            if (node.op in ("elemwise_mul", "broadcast_mul")
                    and len(node.inputs) == 2
                    and _same_entry(node.inputs[0], node.inputs[1])):
                sq = g.new_node("square", node.name, {}, [node.inputs[0]])
                entry_map[(id(node), 0)] = (sq, 0)
                g.note(self.name, "mul_self_to_square", "replace",
                       node=sq.name, origins=[node.name])
                fired += 1
                continue

            # positive reduction axis -> negative canonical form
            if node.op in self._REDUCES and node.inputs:
                ax = parsed.get("axis")
                in_sh = shapes.get((id(node.inputs[0][0]), node.inputs[0][1]))
                if (ax and in_sh is not None
                        and any(a >= 0 for a in ax)
                        and all(-len(in_sh) <= a < len(in_sh) for a in ax)):
                    neg = tuple(a - len(in_sh) if a >= 0 else a for a in ax)
                    node.attrs["axis"] = str(neg if len(neg) > 1 else neg[0])
                    node._parsed = None
                    g.note(self.name, "negative_axis", "attr",
                           node=node.name, origins=[node.name])
                    fired += 1
                continue

            # bare relu op -> Activation(act_type=relu)
            if node.op == "relu":
                act = g.new_node("Activation", node.name,
                                 {"act_type": "relu"}, list(node.inputs))
                entry_map[(id(node), 0)] = (act, 0)
                g.note(self.name, "relu_to_activation", "replace",
                       node=act.name, origins=[node.name])
                fired += 1
                continue

            # reciprocal(sqrt(x)) / 1/sqrt(x) -> rsqrt(x)
            recip = (node.op == "reciprocal"
                     or (node.op == "_rdiv_scalar"
                         and parsed.get("scalar") == 1.0))
            if recip and node.inputs and node.inputs[0][1] == 0:
                prod = node.inputs[0][0]
                if not prod.is_variable and prod.op == "sqrt":
                    rs = g.new_node("rsqrt", node.name, {},
                                    list(prod.inputs))
                    entry_map[(id(node), 0)] = (rs, 0)
                    g.note(self.name, "rsqrt_compose", "replace",
                           node=rs.name, origins=[node.name, prod.name])
                    fired += 1
                    continue

            # identity ops vanish (never when the node IS a program output:
            # eliding it would rename the output entry, and never when the
            # op changed the dtype: int32 * 1.0 PROMOTES to float32, so
            # eliding it would rewrite the computation's type)
            elide = (node.op == "_copy"
                     or (node.op in ("_mul_scalar", "_div_scalar")
                         and parsed.get("scalar") == 1.0))
            if elide and node.inputs:
                in_dt = dtypes.get((id(node.inputs[0][0]),
                                    node.inputs[0][1]))
                out_dt = dtypes.get((id(node), 0))
                if in_dt is None or out_dt is None \
                        or np.dtype(in_dt) != np.dtype(out_dt):
                    elide = False
            if elide and id(node) not in out_ids and node.inputs:
                entry_map[(id(node), 0)] = node.inputs[0]
                # counts["removed"] is DCE's alone — the sweep counts this
                # node once it is actually unreachable, never twice
                g.note(self.name, "identity_elide", "remove",
                       origins=[node.name])
                fired += 1
        g.apply_entry_map(entry_map)
        return fired


# ------------------------------------------------------------------- bf16
class Bf16LegalizePass(RewritePass):
    """Cast-sandwich dtype legalization for MXU-bound ops: every f32 input
    slot an op declares in ``ops/infer_meta.py`` ``bf16_slots`` gets a
    ``Cast(bfloat16)``, and the op's output a ``Cast(float32)`` — compute
    runs on the bf16 MXU fast path, every downstream dtype is unchanged
    (GL601-clean by construction). Opt-in via ``MXNET_GRAPHREWRITE_BF16=1``;
    parity against the f32 graph is by documented tolerance, not bitwise
    (docs/static_analysis.md §GL6xx). Idempotent: legalized nodes carry a
    ``__bf16_legalized__`` marker attr."""

    name = "bf16"

    def run(self, g: _RGraph) -> int:
        fired = 0
        # one inference + one entry-map application for the whole pass:
        # legalizing a node never changes another node's f32-ness (the
        # out-cast restores float32), so the pre-pass tables stay valid
        shapes_tbl, dtypes = g.infer()
        entry_map, out_casts = {}, []
        out_ids = g.output_ids()
        for node in list(g.topo()):
            if node.is_variable or node.attrs.get("__bf16_legalized__"):
                continue
            if id(node) in out_ids:
                continue  # the f32out cast would rename the output entry
            meta = get_meta(node.op)
            if not meta.bf16_slots or node.num_outputs() != 1:
                continue
            try:
                parsed = node.parsed_attrs()
                slots = node.opdef().input_names(parsed)
            except Exception:
                continue
            cast_idx = []
            for i, slot in enumerate(slots[:len(node.inputs)]):
                if slot not in meta.bf16_slots:
                    continue
                dt = dtypes.get((id(node.inputs[i][0]), node.inputs[i][1]))
                if dt is not None and np.dtype(dt) == np.dtype(np.float32):
                    cast_idx.append(i)
            out_dt = dtypes.get((id(node), 0))
            if not cast_idx or out_dt is None \
                    or np.dtype(out_dt) != np.dtype(np.float32):
                continue
            for i in cast_idx:
                src, src_oi = node.inputs[i]
                if src.is_variable and "__shape__" not in src.attrs:
                    # the Cast hides this variable from the consumer's
                    # backward shape rule (simple_bind deduces FC/conv
                    # weight shapes through it) — stamp the shape the
                    # rewrite-time inference already deduced
                    known = shapes_tbl.get((id(src), src_oi))
                    if known is not None:
                        src.attrs["__shape__"] = str(tuple(known))
                cast = g.new_node("Cast", "%s_bf16in%d" % (node.name, i),
                                  {"dtype": "bfloat16"}, [node.inputs[i]])
                node.inputs[i] = (cast, 0)
                g.note(self.name, "cast_in", "insert", node=cast.name,
                       origins=[node.name])
                g.counts["casts"] += 1
            node.attrs["__bf16_legalized__"] = "1"
            node._parsed = None
            back = g.new_node("Cast", node.name + "_f32out",
                              {"dtype": "float32"}, [(node, 0)])
            g.note(self.name, "cast_out", "insert", node=back.name,
                   origins=[node.name])
            g.counts["casts"] += 1
            entry_map[(id(node), 0)] = (back, 0)
            out_casts.append(back)
            fired += 1
        g.apply_entry_map(entry_map, skip_nodes=out_casts)
        return fired


# -------------------------------------------------------------------- dce
class DCEPass(RewritePass):
    """Sweep what the other passes orphaned. The Symbol representation is
    reachability-based — ``live`` is the tracked node set as of the last
    sweep, and anything no longer reachable from the outputs is dead code
    this pass counts (and records provenance for), so GL605's removed
    total is exact rather than implied."""

    name = "dce"

    def run(self, g: _RGraph) -> int:
        reach = {id(n) for n in g.topo()}
        removed = [n for n in g.live if id(n) not in reach]
        for n in removed:
            g.note(self.name, "unreachable", "remove", origins=[n.name])
            g.counts["removed"] += 1
        g.live = g.topo()
        return len(removed)


_BUILTIN = {p.name: p for p in
            (ConstFoldPass(), CSEPass(), CanonicalizePass(),
             Bf16LegalizePass(), DCEPass())}
#: default pipeline order (bf16 joins before dce when enabled)
_DEFAULT_ORDER = ("const_fold", "cse", "canonicalize", "dce")


def rewrite_pass_names():
    return tuple(_BUILTIN)


# ------------------------------------------------------------------ result
class RewriteResult:
    """One pipeline run: the rewritten Symbol plus everything the GL6xx
    verifier needs — the original, the provenance records, per-pass
    firing stats, created-node names, and the fixpoint outcome."""

    def __init__(self, original, symbol, records, counts, pass_fired,
                 created_names, nodes_before, nodes_after, rounds, fixpoint,
                 round_budget, shapes, types, label="", pass_rows=()):
        self.original = original
        self.symbol = symbol
        self.records = records
        self.counts = counts
        self.pass_fired = pass_fired        # {pass: total firings}
        self.created_names = created_names  # names of reachable new nodes
        self.nodes_before = nodes_before
        self.nodes_after = nodes_after
        self.rounds = rounds
        self.fixpoint = fixpoint
        self.round_budget = round_budget
        self.shapes = dict(shapes or {})
        self.types = dict(types or {})
        self.label = label
        # one row per pass execution: {round, pass, fired, nodes_before,
        # nodes_after} — the graphlint --rewrite per-pass table
        self.pass_rows = list(pass_rows)

    @property
    def changed(self) -> bool:
        return bool(self.records)

    def rule_table(self) -> Dict[str, int]:
        """fired-rule histogram: 'pass.rule' -> count."""
        table: Dict[str, int] = {}
        for r in self.records:
            key = "%s.%s" % (r["pass"], r["rule"])
            table[key] = table.get(key, 0) + 1
        return table

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "nodes_before": self.nodes_before,
            "nodes_after": self.nodes_after,
            "counts": dict(self.counts),
            "pass_fired": dict(self.pass_fired),
            "pass_rows": list(self.pass_rows),
            "rules": self.rule_table(),
            "rounds": self.rounds,
            "fixpoint": self.fixpoint,
        }


def rewrite(symbol, shapes=None, types=None, passes=None, bf16=None,
            max_rounds=None, label="") -> RewriteResult:
    """Run the rewrite pipeline over ``symbol`` and return a
    ``RewriteResult`` (the input Symbol is never mutated).

    ``shapes``/``types`` are the bind hints (same contract as ``lint``);
    they power the shape-dependent rules (axis canonicalization, bf16
    slot dtypes) and the verifier. ``passes`` selects a subset by name
    (or supplies ``RewritePass`` instances — the test hook for the GL602
    provenance check); default: const_fold → cse → canonicalize
    [→ bf16 when ``bf16``/``MXNET_GRAPHREWRITE_BF16=1``] → dce, iterated
    to fixpoint within ``max_rounds`` (``MXNET_GRAPHREWRITE_ROUNDS``,
    default 4)."""
    if bf16 is None:
        bf16 = _bf16_enabled()
    if passes is None:
        order = list(_DEFAULT_ORDER)
        if bf16:
            order.insert(-1, "bf16")
        selected = [_BUILTIN[n] for n in order]
    else:
        selected = []
        for p in passes:
            if isinstance(p, str):
                if p not in _BUILTIN:
                    raise ValueError("unknown rewrite pass %r; have: %s"
                                     % (p, sorted(_BUILTIN)))
                selected.append(_BUILTIN[p])
            else:
                selected.append(p)
    budget = max_rounds if max_rounds else _max_rounds()

    g = _RGraph(symbol, shapes=shapes, types=types)
    nodes_before = len(g.live)
    pass_fired = {p.name: 0 for p in selected}
    pass_rows = []
    rounds, fixpoint = 0, False
    if _tm.enabled():
        _tm.counter("rewrite.runs").inc()
    for rounds in range(1, budget + 1):
        round_fired = 0
        for p in selected:
            before = len(g.topo())
            sp = _tm.NULL_SPAN
            if _tm.enabled():
                sp = _tm.span("rewrite.pass", pass_name=p.name)
            with sp:
                n = p.run(g)
                sp.set(fired=n)
            pass_fired[p.name] += n
            round_fired += n
            if n:
                pass_rows.append({"round": rounds, "pass": p.name,
                                  "fired": n, "nodes_before": before,
                                  "nodes_after": len(g.topo())})
        if round_fired == 0:
            fixpoint = True
            break
    final = g.topo()
    reach = {id(n) for n in final}
    created_names = [n.name for i, n in g.created.items() if i in reach]
    if _tm.enabled():
        for key, counter in (("folded", "rewrite.nodes_folded"),
                             ("merged", "rewrite.nodes_merged"),
                             ("removed", "rewrite.nodes_removed"),
                             ("casts", "rewrite.casts_inserted")):
            if g.counts[key]:
                _tm.counter(counter).inc(g.counts[key])
    return RewriteResult(
        original=symbol, symbol=g.symbol(), records=g.records,
        counts=g.counts, pass_fired=pass_fired,
        created_names=created_names, nodes_before=nodes_before,
        nodes_after=len(final), rounds=rounds, fixpoint=fixpoint,
        round_budget=budget, shapes=shapes, types=types, label=label,
        pass_rows=pass_rows)


# ---------------------------------------------------------------- verifier
def _entry_tables(symbol, shapes, types):
    """Partial-mode shape/dtype inference: per-output (shape, dtype) lists
    plus a name -> output-bytes map for the bytes-saved estimate. Never
    raises — an uninferrable graph returns Nones."""
    try:
        res = symbol._infer_impl(
            {k: tuple(v) for k, v in (shapes or {}).items()},
            {k: np_dtype(v) for k, v in (types or {}).items()},
            partial=True)
    except Exception as exc:
        return None, None, str(exc)
    out_shapes, out_types = res[1], res[4]
    return list(out_shapes), list(out_types), None


def _node_bytes(symbol, shapes, types):
    """name -> total output bytes per node (0 when unknown)."""
    from .manager import GraphContext
    from .shape_lint import propagate

    try:
        ctx = GraphContext(symbol, shape_hints=shapes, type_hints=types,
                           strict_shapes=False)
        propagate(ctx)
    except Exception:
        return {}
    table = {}
    for node in ctx.topo:
        total = 0
        for i in range(node.num_outputs()):
            sh = ctx.entry_shape.get((id(node), i))
            dt = ctx.entry_dtype.get((id(node), i))
            if sh is not None:
                total += int(np.prod(sh)) * (np.dtype(dt).itemsize
                                             if dt is not None else 4)
        table[node.name] = table.get(node.name, 0) + total
    return table


def verify_rewrite(result: RewriteResult, grad_req=None,
                   target="") -> Report:
    """Statically check one ``RewriteResult`` against the GL6xx contract.

    ``grad_req`` (optional) is the bind's per-argument request — a dict
    ``{name: req}`` or a list aligned with the ORIGINAL symbol's
    ``list_arguments()`` — and arms GL604. Returns a ``Report`` whose
    ``rewrite_summary`` carries the machine counts + bytes-saved."""
    rep = Report(target=target or result.label or "rewrite")
    orig, new = result.original, result.symbol

    # --- GL601: the output interface must be unchanged -------------------
    o_sh, o_dt, o_err = _entry_tables(orig, result.shapes, result.types)
    n_sh, n_dt, n_err = _entry_tables(new, result.shapes, result.types)
    if n_err is not None:
        rep.add(Diagnostic(
            "GL601", "rewritten graph fails shape/dtype inference: %s"
            % n_err,
            fix_hint="a rewrite pass emitted an unbindable node; run with "
                     "MXNET_GRAPHREWRITE=0 and report the pass"))
    elif o_err is None:
        if len(o_sh) != len(n_sh):
            rep.add(Diagnostic(
                "GL601", "rewrite changed the output count: %d -> %d"
                % (len(o_sh), len(n_sh))))
        else:
            names = orig.list_outputs()
            for i, (a, b, da, db) in enumerate(zip(o_sh, n_sh, o_dt, n_dt)):
                if (a is not None and b is not None and tuple(a) != tuple(b)) \
                        or (da is not None and db is not None
                            and np.dtype(da) != np.dtype(db)):
                    rep.add(Diagnostic(
                        "GL601",
                        "output %d (%s): shape/dtype %s/%s -> %s/%s"
                        % (i, names[i] if i < len(names) else "?",
                           a, getattr(da, "name", da),
                           b, getattr(db, "name", db)),
                        node=names[i] if i < len(names) else None))
    try:
        o_onames, n_onames = orig.list_outputs(), new.list_outputs()
    except Exception:
        o_onames = n_onames = None
    if o_onames is not None and o_onames != n_onames:
        rep.add(Diagnostic(
            "GL601",
            "rewrite changed output names: %s -> %s"
            % (o_onames, n_onames),
            fix_hint="a replacement that owns a program output must keep "
                     "the replaced node's name"))
    o_args, n_args = orig.list_arguments(), new.list_arguments()
    o_aux, n_aux = (orig.list_auxiliary_states(),
                    new.list_auxiliary_states())
    added = [a for a in n_args if a not in set(o_args)]
    if added or o_aux != n_aux or \
            [a for a in o_args if a in set(n_args)] != n_args:
        rep.add(Diagnostic(
            "GL601",
            "rewrite changed the argument interface: args %s -> %s, "
            "aux %s -> %s" % (o_args, n_args, o_aux, n_aux),
            fix_hint="rewrites may drop unused arguments but never add or "
                     "reorder them"))

    # --- GL604: eliminated arguments a grad_req still references ---------
    if grad_req is not None:
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in o_args}
        elif isinstance(grad_req, dict):
            reqs = dict(grad_req)
        else:
            reqs = dict(zip(o_args, grad_req))
        kept = set(n_args)
        for name in o_args:
            if name not in kept and reqs.get(name, "null") != "null":
                rep.add(Diagnostic(
                    "GL604",
                    "argument %r was eliminated by the rewrite but its "
                    "grad_req is %r — backward would write a gradient for "
                    "a value the program never computes"
                    % (name, reqs.get(name)),
                    node=name,
                    fix_hint="set grad_req='null' for %s or disable the "
                             "eliminating pass" % name))

    # --- GL602: every surviving created node needs an originating rule ---
    claimed = {r["node"] for r in result.records if r.get("node")}
    for name in result.created_names:
        # a created node may legitimately share the replaced node's name
        # (canonicalize keeps names stable); claims are by name
        if name not in claimed:
            rep.add(Diagnostic(
                "GL602",
                "node %r was created by a rewrite pass but no provenance "
                "record names it" % name, node=name,
                fix_hint="every pass must g.note() each node it creates"))

    # --- GL603: fixpoint budget ------------------------------------------
    if not result.fixpoint:
        rep.add(Diagnostic(
            "GL603",
            "pipeline still firing after %d round(s) (budget %d) — passes "
            "are ping-ponging or the budget is too small"
            % (result.rounds, result.round_budget),
            fix_hint="raise MXNET_GRAPHREWRITE_ROUNDS or report the "
                     "oscillating rule pair"))

    # --- GL605: the summary ----------------------------------------------
    summary = result.to_dict()
    if result.changed:
        # NET intermediate bytes eliminated: every origin of an
        # eliminating record, deduped by name (a merged node gets both a
        # merge record and DCE's sweep record — count it once), MINUS the
        # bytes of surviving pass-created nodes (a square replacing a
        # self-multiply eliminated nothing)
        obytes = _node_bytes(orig, result.shapes, result.types)
        gone = set()
        for r in result.records:
            if r["action"] in ("fold", "merge", "remove"):
                gone.update(r["origins"])
        nbytes = _node_bytes(new, result.shapes, result.types)
        bytes_saved = max(0, sum(obytes.get(n, 0) for n in gone)
                          - sum(nbytes.get(n, 0)
                                for n in set(result.created_names)))
        summary["bytes_saved_estimate"] = int(bytes_saved)
        rep.add(Diagnostic(
            "GL605",
            "%d node(s) -> %d: %d folded, %d merged, %d removed, %d casts "
            "inserted (~%.1f KiB of per-step intermediates eliminated)"
            % (result.nodes_before, result.nodes_after,
               result.counts["folded"], result.counts["merged"],
               result.counts["removed"], result.counts["casts"],
               bytes_saved / 1024.0)))
    rep.rewrite_summary = summary
    return rep


# ------------------------------------------------------------ bind helper
def pattern_site_counts(symbol) -> Dict[str, int]:
    """Per-pattern fusion site counts the fusion engine would root on this
    symbol — the before/after metric of the canonicalization pass (the
    ``graphlint --rewrite`` dump and the CI gate read it)."""
    from .. import fusion

    plan = fusion.plan(symbol._topo(),
                       output_ids={id(n) for n, _ in symbol._outputs})
    return fusion.plan_sites(plan)[0]


def rewrite_for_bind(symbol, shapes, types, grad_req=None, target="bind"):
    """The ``executor.bind``/``SPMDStepAdapter`` hook: rewrite under the
    ``MXNET_GRAPHREWRITE`` gate and return the symbol the program should
    bind (the ORIGINAL on any fallback — a rewrite failure must never sink
    a bind).

    ``verify`` mode runs the GL6xx verifier and raises ``MXNetError`` on
    any error-severity finding (GL601/GL602/GL604). A rewrite whose
    argument interface drifted is abandoned even under ``on`` — positional
    binds and exec-group layouts depend on it."""
    mode = graphrewrite_mode()
    if mode is None:
        return symbol, None
    try:
        result = rewrite(symbol, shapes=shapes, types=types, label=target)
    except Exception as exc:
        if _tm.enabled():
            _tm.counter("rewrite.fallbacks").inc()
        _LOG.warning("graph rewrite failed at %s (%s: %s) — binding the "
                     "original graph", target, type(exc).__name__, exc)
        return symbol, None
    if not result.changed:
        return symbol, result
    if mode == "verify":
        report = verify_rewrite(result, grad_req=grad_req, target=target)
        for d in report:
            lvl = (logging.ERROR if d.severity == "error" else
                   logging.WARNING if d.severity == "warning" else
                   logging.DEBUG)
            _LOG.log(lvl, d.format())
        if report.errors:
            raise MXNetError(
                "graph rewrite verification failed at %s "
                "(MXNET_GRAPHREWRITE=verify):\n%s"
                % (target, report.format(min_severity="warning")))
    # interface stability is load-bearing in BOTH modes: the verifier
    # tolerates dropping an unused argument (GL604 only fires when it is
    # grad_req'd), but a positional bind counts its args — fall back
    # rather than sink the bind
    if (result.symbol.list_arguments() != symbol.list_arguments()
            or result.symbol.list_auxiliary_states()
            != symbol.list_auxiliary_states()):
        if _tm.enabled():
            _tm.counter("rewrite.fallbacks").inc()
        _LOG.warning("graph rewrite at %s changed the argument "
                     "interface — binding the original graph", target)
        return symbol, None
    return result.symbol, result
