"""``tools/graphlint`` CLI implementation.

Lints bundled model-zoo networks (by name) or serialized Symbol JSON files
(by path) with the full static-analysis pass suite and prints structured
diagnostics. Exit code: 0 clean, 1 findings at/above the failure severity
(error by default, warning with ``--strict``), 2 usage or load failure.

Examples::

    python tools/graphlint resnet-18 --shape data=1,3,32,32
    python tools/graphlint model-symbol.json --format json
    python tools/graphlint --all-models
    python tools/graphlint --list-codes
    python tools/graphlint resnet-50 --shape data=32,3,224,224 \
        --mesh dp=8,model=2 --budget-gb 16   # sharding-plan + HBM planner
    python tools/graphlint transformer --rewrite       # GL6xx rewrite dump
    python tools/graphlint --all-models --rewrite --format json
    python tools/graphlint --dispatch                  # GL7xx host-sync lint
    python tools/graphlint --dispatch mxnet_tpu/serving --format json
    python tools/graphlint --dispatch --trace profile.json   # + GL705
    python tools/graphlint --concurrency               # GL8xx lock/collective lint
    python tools/graphlint --concurrency mxnet_tpu/serving --format json
    python tools/graphlint --concurrency --witness trace.json   # + GL805
"""
from __future__ import annotations

import argparse
import json
import sys

from .diagnostics import CODES, describe_code

# Default lint shapes/dtypes per zoo model: enough hints that the full
# shape/dtype propagation runs end to end (labels backward-derive via
# shape_rules where possible). Models without an entry lint structurally.
DEFAULT_SHAPES = {
    "lenet": {"data": (1, 1, 28, 28)},
    "mlp": {"data": (1, 784)},
    "alexnet": {"data": (1, 3, 224, 224)},
    "vgg": {"data": (1, 3, 224, 224)},
    "vgg16": {"data": (1, 3, 224, 224)},
    "vgg19": {"data": (1, 3, 224, 224)},
    "inception-bn": {"data": (1, 3, 224, 224)},
    "inception_bn": {"data": (1, 3, 224, 224)},
    "inception-v3": {"data": (1, 3, 299, 299)},
    "inception_v3": {"data": (1, 3, 299, 299)},
    "resnet": {"data": (1, 3, 224, 224)},
    "resnet-18": {"data": (1, 3, 224, 224)},
    "resnet-34": {"data": (1, 3, 224, 224)},
    "resnet-50": {"data": (1, 3, 224, 224)},
    "resnet-101": {"data": (1, 3, 224, 224)},
    "resnet-152": {"data": (1, 3, 224, 224)},
    "lstm": {"data": (32, 32), "softmax_label": (32, 32)},
    "transformer": {"data": (2, 64), "softmax_label": (2, 64)},
    "transformer_mt": {"data": (2, 64), "dec_data": (2, 64),
                       "softmax_label": (2, 64)},
    "vgg16-ssd-300": {"data": (1, 3, 300, 300)},
    "vgg16-ssd-300-train": {"data": (1, 3, 300, 300), "label": (1, 3, 5)},
    "recommender": {"user": (64,), "item": (64,), "dense": (64, 16),
                    "label": (64,)},
    "dlrm": {"user": (64,), "item": (64,), "dense": (64, 16),
             "label": (64,)},
}
DEFAULT_TYPES = {
    "lstm": {"data": "int32"},
    "transformer": {"data": "int32"},
    "transformer_mt": {"data": "int32", "dec_data": "int32"},
    "recommender": {"user": "int32", "item": "int32"},
    "dlrm": {"user": "int32", "item": "int32"},
}


def _parse_kv_shape(spec: str):
    if "=" not in spec:
        raise ValueError("--shape expects NAME=d0,d1,... got %r" % spec)
    name, dims = spec.split("=", 1)
    shape = tuple(int(x) for x in dims.strip("()[] ").split(",") if x.strip())
    return name.strip(), shape


def _parse_kv_type(spec: str):
    if "=" not in spec:
        raise ValueError("--type expects NAME=dtype, got %r" % spec)
    name, dt = spec.split("=", 1)
    return name.strip(), dt.strip()


def _zoo_sweep_names():
    """Deduped zoo keys for --all-models (aliases collapse to one entry)."""
    from ..models import _ZOO

    seen, names = set(), []
    for key in sorted(_ZOO):
        fn = _ZOO[key]
        marker = getattr(fn, "__wrapped__", None) or fn
        if id(marker) in seen:
            continue
        seen.add(id(marker))
        names.append(key)
    return names


def _load_target(name, shapes, types, use_defaults):
    """Resolve one CLI target to (label, symbol, shape_hints, type_hints)."""
    if name.endswith(".json"):
        from .. import symbol as sym_mod

        return name, sym_mod.load(name), dict(shapes), dict(types)
    from .. import models

    sym = models.get_symbol(name)
    key = name.lower()  # get_symbol lowercases; the shape table must too
    sh = dict(DEFAULT_SHAPES.get(key, {})) if use_defaults else {}
    ty = dict(DEFAULT_TYPES.get(key, {})) if use_defaults else {}
    sh.update(shapes)
    ty.update(types)
    return name, sym, sh, ty


def _format_plan(plan) -> str:
    """Human block for one target's memory plan: the per-device byte table
    plus the peak owner and its live set."""
    from .shard_lint import fmt_bytes

    pd = plan["per_device"]
    mesh = plan["mesh"]
    head = "-- predicted peak HBM per device: %s (%s, %s%s) --" % (
        fmt_bytes(pd["peak"]),
        "train/" + plan["policy"] if plan["train"] else "inference",
        "mesh " + ",".join("%s=%d" % kv for kv in mesh.items())
        if mesh else "single device",
        ", budget %s" % fmt_bytes(plan["budget_bytes"])
        if plan["budget_bytes"] else "")
    lines = [head]
    lines.append("   params %s | grads %s | opt %s | inputs %s | "
                 "activations %s"
                 % (fmt_bytes(pd["params"]), fmt_bytes(pd["grads"]),
                    fmt_bytes(pd["opt_state"]), fmt_bytes(pd["inputs"]),
                    fmt_bytes(pd["act_peak"])))
    lines.append("   peak at %s (%s); largest live: %s"
                 % (plan["peak_node"], plan["peak_phase"],
                    ", ".join("%s=%s" % (n, fmt_bytes(b))
                              for n, b in plan["peak_live"][:4]) or "-"))
    return "\n".join(lines)


def _format_peak_table(peaks) -> str:
    """The --all-models summary: one peak-HBM row per target."""
    from .shard_lint import fmt_bytes

    rows = [("model", "peak/device", "params", "activations", "peak node")]
    for label, plan in peaks:
        if plan is None:
            rows.append((label, "n/a (shapes underdetermined)", "-", "-", "-"))
            continue
        pd = plan["per_device"]
        rows.append((label, fmt_bytes(pd["peak"]), fmt_bytes(pd["params"]),
                     fmt_bytes(pd["act_peak"]), str(plan["peak_node"])))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = ["== peak-HBM summary =="]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def _format_plan_table(rows) -> str:
    """The --autoplan --all-models summary: one plan row per target."""
    from .shard_lint import fmt_bytes

    table = [("model", "mesh", "pp", "comm/step", "peak/device", "verdict")]
    for label, plan, err in rows:
        if plan is None:
            table.append((label, "-", "-", "-", "-", "ERROR: %s" % err))
            continue
        mesh = ",".join("%s=%d" % kv for kv in plan.mesh.items())
        table.append((
            label, mesh,
            str(plan.pipeline_stages) if plan.pipeline_stages > 1 else "-",
            fmt_bytes(plan.predicted.get("comm_bytes", 0)),
            fmt_bytes(plan.predicted.get("peak_bytes", 0)),
            "ok" if plan.feasible else "INFEASIBLE"))
    widths = [max(len(r[i]) for r in table) for i in range(len(table[0]))]
    out = ["== autoplan summary =="]
    for r in table:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def _run_autoplan(args, targets, shapes, types, devices) -> int:
    """The --autoplan mode: plan every target, dump the ParallelPlans.

    Exit 0 when every target got a plan — feasible OR infeasible-with-a-
    structured-reason (the CI gate's contract); 1 when the planner itself
    failed on any target; 2 on load failures."""
    from ..parallel import autoplan

    rows = []
    load_failed = plan_failed = False
    for target in targets:
        try:
            label, sym, sh, ty = _load_target(
                target, shapes, types, not args.no_default_shapes)
        except Exception as exc:
            print("graphlint: cannot load %r: %s: %s"
                  % (target, type(exc).__name__, exc), file=sys.stderr)
            rows.append((target, None, "load: %s" % exc))
            load_failed = True
            continue
        try:
            plan = autoplan.plan_parallel(
                sym, sh, types=ty, devices=devices,
                budget_gb=args.budget_gb, bwd=args.bwd, label=label)
        except autoplan.PlanError as exc:
            rows.append((label, None, str(exc)))
            plan_failed = True
            continue
        rows.append((label, plan, None))

    if args.format == "json":
        payload = []
        for label, plan, err in rows:
            entry = {"target": label, "devices": devices}
            if plan is None:
                entry["plan_error"] = err
            else:
                entry["autoplan"] = plan.to_dict()
            payload.append(entry)
        print(json.dumps(payload, indent=2))
    else:
        for label, plan, err in rows:
            print("== autoplan: %s (%d devices) ==" % (label, devices))
            if plan is None:
                print("  planner failed: %s" % err)
                continue
            print("  " + plan.summary())
            if not plan.feasible:
                print("  reason: %s" % plan.reason)
            if plan.stage_cuts:
                print("  stage cuts: %s" % ", ".join(plan.stage_cuts))
            for rej in plan.rejected[:4]:
                mesh = ",".join("%s=%d" % kv for kv in rej["mesh"].items())
                print("  rejected mesh[%s]: %s" % (mesh, rej["why"]))
            print()
        if len(rows) > 1:
            print(_format_plan_table(rows))
    if load_failed:
        return 2
    return 1 if plan_failed else 0


def _format_rewrite(label, res, report, sites_before, sites_after) -> str:
    """Human block for one target's rewrite run: per-pass node-count table,
    fired-rule histogram, fusion-site delta, verifier outcome."""
    lines = ["== graphrewrite: %s ==" % label]
    lines.append("nodes %d -> %d (%d folded, %d merged, %d removed, "
                 "%d casts) rounds=%d fixpoint=%s"
                 % (res.nodes_before, res.nodes_after,
                    res.counts["folded"], res.counts["merged"],
                    res.counts["removed"], res.counts["casts"],
                    res.rounds, "yes" if res.fixpoint else "NO"))
    if res.pass_rows:
        rows = [("round", "pass", "fired", "nodes before", "nodes after")]
        for r in res.pass_rows:
            rows.append((str(r["round"]), r["pass"], str(r["fired"]),
                         str(r["nodes_before"]), str(r["nodes_after"])))
        widths = [max(len(x[i]) for x in rows) for i in range(5)]
        lines.extend("  " + "  ".join(c.ljust(w)
                                      for c, w in zip(r, widths)).rstrip()
                     for r in rows)
    rules = res.rule_table()
    if rules:
        lines.append("fired rules:")
        lines.extend("  %-32s %d" % (k, v) for k, v in sorted(rules.items()))
    if sites_before != sites_after:
        names = sorted(set(sites_before) | set(sites_after))
        lines.append("fusion sites: " + ", ".join(
            "%s %d -> %d" % (n, sites_before.get(n, 0), sites_after.get(n, 0))
            for n in names))
    if report is not None:
        bad = [d for d in report
               if d.code in ("GL601", "GL602", "GL603", "GL604")]
        if bad:
            lines.extend(d.format() for d in bad)
        else:
            lines.append("verify: clean (0 errors)")
        for d in report.by_code("GL605"):
            lines.append(d.format())
    return "\n".join(lines)


def _format_rewrite_table(rows) -> str:
    """The --rewrite --all-models summary: one rewrite row per target."""
    table = [("model", "nodes", "folded/merged/removed", "norm_residual",
              "verdict")]
    for label, res, report, sb, sa, err in rows:
        if res is None:
            table.append((label, "-", "-", "-", "ERROR: %s" % err))
            continue
        codes = sorted({d.code for d in report.errors}) if report else []
        table.append((
            label, "%d->%d" % (res.nodes_before, res.nodes_after),
            "%d/%d/%d" % (res.counts["folded"], res.counts["merged"],
                          res.counts["removed"]),
            "%d->%d" % (sb.get("norm_residual", 0),
                        sa.get("norm_residual", 0)),
            "ok" if not codes else ",".join(codes)))
    widths = [max(len(r[i]) for r in table) for i in range(len(table[0]))]
    out = ["== graphrewrite summary =="]
    for r in table:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def _run_rewrite(args, targets, shapes, types) -> int:
    """The --rewrite mode: rewrite every target (analysis/rewrite.py), run
    the GL6xx verifier, dump per-pass node counts + the fired-rule table +
    the fusion-site delta. ``--rewrite-json`` adds the full provenance
    record list to the JSON payload.

    Exit 0 when every target rewrites and verifies with zero
    GL601/GL602/GL604; 1 on any verifier error (or rewrite crash); 2 on
    load failure."""
    from . import verify_rewrite
    from .rewrite import pattern_site_counts, rewrite as run_rewrite

    rows, payload = [], []
    load_failed = verify_failed = False
    for target in targets:
        try:
            label, sym, sh, ty = _load_target(
                target, shapes, types, not args.no_default_shapes)
        except Exception as exc:
            print("graphlint: cannot load %r: %s: %s"
                  % (target, type(exc).__name__, exc), file=sys.stderr)
            rows.append((target, None, None, {}, {}, str(exc)))
            payload.append({"target": target, "load_error": str(exc)})
            load_failed = True
            continue
        try:
            res = run_rewrite(sym, shapes=sh, types=ty, label=label)
            report = verify_rewrite(res, target=label)
            sites_before = pattern_site_counts(sym)
            sites_after = pattern_site_counts(res.symbol)
        except Exception as exc:
            print("graphlint: rewrite of %r failed: %s: %s"
                  % (label, type(exc).__name__, exc), file=sys.stderr)
            rows.append((label, None, None, {}, {}, str(exc)))
            payload.append({"target": label, "rewrite_error": str(exc)})
            verify_failed = True
            continue
        if report.errors:
            verify_failed = True
        rows.append((label, res, report, sites_before, sites_after, None))
        entry = {"target": label, "rewrite": res.to_dict(),
                 "fusion_sites_before": sites_before,
                 "fusion_sites_after": sites_after,
                 "verify": json.loads(report.to_json())}
        if args.rewrite_json:
            entry["records"] = res.records
        payload.append(entry)
    if args.format == "json" or args.rewrite_json:
        print(json.dumps(payload, indent=2))
    else:
        for label, res, report, sb, sa, err in rows:
            if res is None:
                continue
            print(_format_rewrite(label, res, report, sb, sa))
            print()
        if len(rows) > 1:
            print(_format_rewrite_table(rows))
    if load_failed:
        return 2
    return 1 if verify_failed else 0


def _format_dispatch_table(sites) -> str:
    """The --dispatch per-site table: one row per finding, waiver column."""
    rows = [("code", "site", "function", "waived", "finding")]
    for s in sites:
        msg = s["message"]
        if len(msg) > 56:
            msg = msg[:53] + "..."
        rows.append((s["code"], "%s:%d" % (s["file"], s["line"]),
                     s["function"], "waived" if s["waived"] else "-", msg))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = ["== dispatch sites =="]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def _run_dispatch(args, targets) -> int:
    """The --dispatch mode: the source-level dispatch-discipline lint
    (GL701-GL704, analysis/dispatch_lint.py) over Python files and
    directories instead of Symbol graphs. Targets are *paths*; with none
    given, the default scan surface is the serving hot paths plus the
    benches that drive them (``dispatch_lint.DEFAULT_SCAN_PATHS``).
    ``--trace DUMP.json`` additionally prices a telemetry capture: GL705
    for any span whose measured host gap exceeds
    ``MXNET_DISPATCHLINT_GAP_PCT`` of its device busy time.

    A finding acknowledged with ``# graphlint: waive GL70x -- reason``
    stays in the site table (column ``waived``) but does not fail the
    run. Exit 0 when every static finding is waived (or none) and no
    GL705 fired; 1 otherwise; 2 on an unreadable path or trace."""
    from .dispatch_lint import (DEFAULT_SCAN_PATHS, lint_dispatch_gaps,
                                lint_dispatch_paths)

    try:
        report, sites = lint_dispatch_paths(targets or None)
    except OSError as exc:
        print("graphlint: --dispatch: %s" % exc, file=sys.stderr)
        return 2
    gap_diags = []
    if args.trace:
        from ..telemetry.trace import gap_summary

        try:
            with open(args.trace) as f:
                trace = json.load(f)
        except (OSError, ValueError) as exc:
            print("graphlint: cannot load --trace %s: %s"
                  % (args.trace, exc), file=sys.stderr)
            return 2
        gap_diags = lint_dispatch_gaps(gap_summary(trace=trace, top=1000))
        report.extend(gap_diags)
    failed = any(not s["waived"] for s in sites) or bool(gap_diags)
    if args.format == "json":
        payload = {"target": "dispatch",
                   "paths": list(targets) or list(DEFAULT_SCAN_PATHS),
                   "sites": sites,
                   "gaps": [d.to_dict() for d in gap_diags],
                   "report": json.loads(report.to_json())}
        print(json.dumps(payload, indent=2))
    else:
        print(report.format(min_severity=args.min_severity))
        if sites:
            print()
            print(_format_dispatch_table(sites))
    return 1 if failed else 0


def _format_concurrency_table(sites) -> str:
    """The --concurrency per-site table: one row per finding."""
    rows = [("code", "site", "function", "waived", "finding")]
    for s in sites:
        msg = s["message"]
        if len(msg) > 56:
            msg = msg[:53] + "..."
        rows.append((s["code"], "%s:%d" % (s["file"], s["line"]),
                     s["function"], "waived" if s["waived"] else "-", msg))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = ["== concurrency sites =="]
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
    return "\n".join(out)


def _run_concurrency(args, targets) -> int:
    """The --concurrency mode: the source-level concurrency lint
    (GL801-GL804, analysis/concurrency_lint.py) over Python files and
    directories. Targets are *paths*; with none given, the default scan
    surface is the threaded/distributed layer
    (``concurrency_lint.DEFAULT_SCAN_PATHS``). ``--witness DUMP.json``
    additionally judges a ``MXNET_CONCLINT=witness`` run: GL805 for every
    witnessed lock-order inversion or >threshold hold across a dispatch
    seam (the dump is either a raw ``witness_report()`` JSON or a chrome
    trace whose ``otherData.lock_witness`` block carries one).

    Waivers (``# graphlint: waive GL80x -- reason``) stay in the site
    table but do not fail the run. Exit 0 when every static finding is
    waived (or none) and no GL805 fired; 1 otherwise; 2 on an unreadable
    path or witness dump."""
    from .concurrency_lint import (DEFAULT_SCAN_PATHS, lint_lock_witness,
                                   lint_concurrency_paths)

    try:
        report, sites = lint_concurrency_paths(targets or None)
    except OSError as exc:
        print("graphlint: --concurrency: %s" % exc, file=sys.stderr)
        return 2
    witness_diags = []
    if args.witness:
        try:
            with open(args.witness) as f:
                dump = json.load(f)
        except (OSError, ValueError) as exc:
            print("graphlint: cannot load --witness %s: %s"
                  % (args.witness, exc), file=sys.stderr)
            return 2
        if isinstance(dump.get("otherData"), dict):
            dump = dump["otherData"].get("lock_witness") or {}
        witness_diags = lint_lock_witness(dump)
        report.extend(witness_diags)
    failed = any(not s["waived"] for s in sites) or bool(witness_diags)
    if args.format == "json":
        payload = {"target": "concurrency",
                   "paths": list(targets) or list(DEFAULT_SCAN_PATHS),
                   "sites": sites,
                   "witness": [d.to_dict() for d in witness_diags],
                   "report": json.loads(report.to_json())}
        print(json.dumps(payload, indent=2))
    else:
        print(report.format(min_severity=args.min_severity))
        if sites:
            print()
            print(_format_concurrency_table(sites))
    return 1 if failed else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graphlint",
        description="Static graph lint for mxnet_tpu Symbols "
                    "(shape/dtype propagation, retrace guard, fusion "
                    "explainer). See docs/static_analysis.md.")
    ap.add_argument("targets", nargs="*",
                    help="model-zoo names (e.g. resnet-18) or *-symbol.json paths")
    ap.add_argument("--all-models", action="store_true",
                    help="lint every bundled model in mxnet_tpu/models/")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="NAME=d0,d1,...",
                    help="shape hint for an input (repeatable)")
    ap.add_argument("--type", action="append", default=[], dest="types",
                    metavar="NAME=dtype",
                    help="dtype hint for an input (repeatable)")
    ap.add_argument("--no-default-shapes", action="store_true",
                    help="lint structurally; skip the built-in per-model "
                         "default shape table")
    ap.add_argument("--mesh", default=None, metavar="AXIS=N[,AXIS=N...]",
                    help="abstract device mesh for the sharding-plan lint "
                         "(GL4xx) and per-device memory planning, e.g. "
                         "dp=8,model=2 — first axis is the batch axis, "
                         "'model' (or the second axis) the tensor axis")
    ap.add_argument("--rewrite", action="store_true",
                    help="run the Symbol->Symbol rewrite pipeline "
                         "(analysis/rewrite.py: const fold, CSE, "
                         "canonicalize, DCE) + the GL6xx provenance "
                         "verifier instead of the lint passes, and dump "
                         "per-pass node counts, the fired-rule table and "
                         "the fusion-site delta per target "
                         "(docs/static_analysis.md §GL6xx)")
    ap.add_argument("--dispatch", action="store_true",
                    help="run the source-level dispatch-discipline lint "
                         "(GL7xx: host sync inside dispatch loops, "
                         "scan-able loops, host-side reductions, premature "
                         "pulls) over Python files/dirs instead of Symbol "
                         "graphs. Targets are paths; default: the serving "
                         "hot paths. Findings carry file:line provenance "
                         "and honor '# graphlint: waive GL70x -- reason' "
                         "comments (docs/static_analysis.md)")
    ap.add_argument("--trace", default=None, metavar="DUMP.json",
                    help="with --dispatch: also price a telemetry "
                         "chrome-trace dump — GL705 when a span's measured "
                         "host gap exceeds MXNET_DISPATCHLINT_GAP_PCT of "
                         "its device busy time")
    ap.add_argument("--concurrency", action="store_true",
                    help="run the source-level concurrency lint (GL8xx: "
                         "rank-divergent collectives, unguarded shared "
                         "state, lock-order inversions, blocking with a "
                         "lock held) over Python files/dirs instead of "
                         "Symbol graphs. Targets are paths; default: the "
                         "threaded/distributed surface. Findings honor "
                         "'# graphlint: waive GL80x -- reason' comments "
                         "(docs/static_analysis.md)")
    ap.add_argument("--witness", default=None, metavar="DUMP.json",
                    help="with --concurrency: also judge a "
                         "MXNET_CONCLINT=witness run — GL805 for every "
                         "witnessed lock-order inversion or >threshold "
                         "hold across a dispatch seam (raw "
                         "witness_report() JSON or a chrome trace with an "
                         "otherData.lock_witness block)")
    ap.add_argument("--rewrite-json", action="store_true",
                    help="with --rewrite: emit the machine-readable plan "
                         "dump as JSON, including the full provenance "
                         "record list")
    ap.add_argument("--autoplan", action="store_true",
                    help="run the cost-model auto-parallel planner "
                         "(parallel.autoplan) instead of the lint passes: "
                         "search dp x tp x pp over --mesh-devices devices "
                         "and dump the winning ParallelPlan per target "
                         "(docs/PARALLEL_PLANNER.md). An infeasible plan "
                         "with a structured reason is a valid outcome "
                         "(exit 0); only a planner failure exits 1")
    ap.add_argument("--mesh-devices", type=int, default=None, metavar="N",
                    help="device count the --autoplan search factorizes "
                         "(defaults to the --mesh product when given)")
    ap.add_argument("--budget-gb", type=float, default=None,
                    help="peak-HBM budget per device in GiB, the unit the "
                         "peak tables print (GL501); default: the "
                         "MXNET_MEMLINT_BUDGET_GB env var")
    ap.add_argument("--bwd", choices=("stash", "recompute"), default="stash",
                    help="memory planner backward policy: stash every "
                         "activation (default, the no-remat executor) or "
                         "keep only MXU-op outputs (remat='dots')")
    ap.add_argument("--inference", action="store_true",
                    help="plan memory without grads/optimizer state "
                         "(forward-only liveness)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--min-severity", choices=("info", "warning", "error"),
                    default="info", help="suppress findings below this level "
                                         "in text output")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail (exit 1)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass subset (default: all)")
    ap.add_argument("--list-codes", action="store_true",
                    help="print every diagnostic code and exit")
    args = ap.parse_args(argv)

    if args.list_codes:
        for code in sorted(CODES):
            print(describe_code(code))
        return 0

    if args.dispatch:
        return _run_dispatch(args, list(args.targets))

    if args.concurrency:
        return _run_concurrency(args, list(args.targets))

    targets = list(args.targets)
    if args.all_models:
        targets.extend(n for n in _zoo_sweep_names() if n not in targets)
    if not targets:
        ap.print_usage(sys.stderr)
        print("graphlint: no targets (give model names, JSON paths, or "
              "--all-models)", file=sys.stderr)
        return 2

    try:
        shapes = dict(_parse_kv_shape(s) for s in args.shape)
        types = dict(_parse_kv_type(s) for s in args.types)
    except ValueError as exc:
        print("graphlint: %s" % exc, file=sys.stderr)
        return 2
    mesh = None
    if args.mesh:
        from ..parallel.mesh import parse_mesh_spec

        try:
            mesh = parse_mesh_spec(args.mesh)
        except ValueError as exc:
            print("graphlint: %s" % exc, file=sys.stderr)
            return 2

    if args.rewrite or args.rewrite_json:
        return _run_rewrite(args, targets, shapes, types)

    if args.autoplan:
        devices = args.mesh_devices
        if devices is None and mesh is not None:
            devices = mesh.size
        if devices is None or devices < 1:
            print("graphlint: --autoplan needs --mesh-devices N (or --mesh)",
                  file=sys.stderr)
            return 2
        return _run_autoplan(args, targets, shapes, types, devices)

    from . import lint

    passes = args.passes.split(",") if args.passes else None
    failed = False
    load_failed = False
    json_out = []
    peaks = []  # (target, plan) rows for the --all-models summary table
    for target in targets:
        try:
            label, sym, sh, ty = _load_target(
                target, shapes, types, not args.no_default_shapes)
        except Exception as exc:
            # keep going: the other targets' reports (and, in json mode,
            # a machine-readable load_error entry) must still come out
            print("graphlint: cannot load %r: %s: %s"
                  % (target, type(exc).__name__, exc), file=sys.stderr)
            if args.format == "json":
                json_out.append({"target": target,
                                 "load_error": "%s: %s"
                                               % (type(exc).__name__, exc),
                                 "diagnostics": []})
            load_failed = True
            continue
        try:
            report = lint(sym, shapes=sh, types=ty, passes=passes,
                          target=label, mesh=mesh,
                          budget_gb=args.budget_gb, bwd=args.bwd,
                          train=not args.inference)
        except ValueError as exc:  # unknown --passes selection
            print("graphlint: %s" % exc, file=sys.stderr)
            return 2
        if not report.ok(strict=args.strict):
            failed = True
        peaks.append((label, report.memory_plan))
        if args.format == "json":
            json_out.append(json.loads(report.to_json()))
        else:
            print(report.format(min_severity=args.min_severity))
            if report.memory_plan is not None:
                print(_format_plan(report.memory_plan))
            print()
    if args.format == "json":
        print(json.dumps(json_out, indent=2))
    elif len(peaks) > 1:
        print(_format_peak_table(peaks))
    if load_failed:
        return 2
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
