"""mxnet_tpu.analysis: static graph-lint & engine-race analysis.

The home for every static pass over the Symbol DAG, executor bind metadata,
and recorded engine schedules (ISSUE 1 tentpole; Relay/PyGraph lineage in
PAPERS.md). Three entry points:

* ``lint(symbol, shapes=..., types=...)`` — run the graph passes, get a
  ``Report`` of structured ``Diagnostic``s (stable ``GLxxx`` codes). Pass
  ``mesh="dp=8,model=2"`` (and optionally ``rules``/``budget_gb``/``bwd``)
  to add the GL4xx sharding-plan lint and the GL5xx per-device peak-HBM
  planner; the planner's table lands on ``Report.memory_plan``.
* ``MXNET_GRAPHLINT=warn|error`` — ``executor.bind``/``simple_bind`` run the
  same passes on every bind; ``warn`` logs, ``error`` raises ``MXNetError``
  with the formatted report instead of a JAX traceback. The fused-step
  path (``module.spmd_adapter``) feeds the passes the REAL mesh + rules.
* ``tools/graphlint`` — the CLI: lints bundled models or a serialized
  Symbol JSON (``python tools/graphlint --all-models``); ``--mesh`` /
  ``--budget-gb`` / ``--bwd`` drive the distributed-plan passes.

Engine schedules are analyzed separately (they are runtime traces, not
graphs): wrap any engine in ``RecordingEngine``, run the workload, then
``analyze_trace(engine.trace)``. See ``docs/static_analysis.md`` for every
diagnostic code.
"""
from __future__ import annotations

import logging
import os

from ..base import MXNetError
from .diagnostics import CODES, Diagnostic, Report, Severity, describe_code
from .dispatch_lint import (dispatch_gap_pct, lint_dispatch_gaps,
                            lint_dispatch_paths, lint_dispatch_source)
from .engine_race import RecordingEngine, ScheduleTrace, analyze_trace
from .manager import GraphContext, graph_pass, list_passes, run_graph_passes
from .rewrite import (RewritePass, RewriteResult, graphrewrite_mode,
                      pattern_site_counts, rewrite, rewrite_pass_names,
                      verify_rewrite)

__all__ = [
    "CODES", "Diagnostic", "Report", "Severity", "describe_code",
    "GraphContext", "graph_pass", "list_passes", "run_graph_passes",
    "RecordingEngine", "ScheduleTrace", "analyze_trace",
    "lint", "lint_bind", "graphlint_mode",
    "rewrite", "verify_rewrite", "graphrewrite_mode", "RewritePass",
    "RewriteResult", "rewrite_pass_names", "pattern_site_counts",
    "lint_dispatch_paths", "lint_dispatch_source", "lint_dispatch_gaps",
    "dispatch_gap_pct",
]

_LOG = logging.getLogger("mxnet_tpu.graphlint")


def lint(symbol, shapes=None, types=None, strict_shapes=None, passes=None,
         target="", mesh=None, rules=None, budget_gb=None, bwd="stash",
         train=True) -> Report:
    """Run the registered graph passes over ``symbol``.

    ``shapes``/``types`` are name->shape / name->dtype hints (same contract
    as ``Symbol.infer_shape``/``infer_type`` kwargs). ``strict_shapes``
    defaults to True when shape hints are given: underdetermined arguments
    are then GL002 errors rather than expected polymorphism (GL203).

    Distributed-plan knobs (docs/static_analysis.md §GL4xx/GL5xx):
    ``mesh`` is a ``parallel.MeshSpec``/jax Mesh/axis dict/``"dp=8,model=2"``
    string enabling the sharding-plan lint; ``rules`` overrides the
    ``ShardingRules`` derived from it. ``budget_gb`` (binary GiB — the unit
    every report line prints; default: the ``MXNET_MEMLINT_BUDGET_GB`` env)
    arms GL501; ``bwd`` is the planner's stash/recompute policy and
    ``train`` toggles grad/optimizer accounting.
    """
    if mesh is not None:
        from ..parallel.mesh import parse_mesh_spec

        mesh = parse_mesh_spec(mesh)
    return run_graph_passes(symbol, shape_hints=shapes, type_hints=types,
                            strict_shapes=strict_shapes, passes=passes,
                            target=target, mesh=mesh, rules=rules,
                            budget_bytes=(None if budget_gb is None
                                          else float(budget_gb) * 2 ** 30),
                            bwd_policy=bwd, train=train)


_warned_modes = set()


def graphlint_mode():
    """The MXNET_GRAPHLINT env knob: None (off, the default), 'warn', or
    'error'. Boolean-style truthy values ('1', 'true', 'on') mean 'warn'
    (every other knob in docs/ENV_VARS.md is 0/1, so honor the idiom);
    anything else logs a one-time warning and stays off rather than letting
    the user believe a gate is active that never runs."""
    raw = os.environ.get("MXNET_GRAPHLINT", "0").strip().lower()
    if raw in ("warn", "error"):
        return raw
    if raw in ("1", "true", "on"):
        return "warn"
    if raw not in ("", "0", "false", "off") and raw not in _warned_modes:
        _warned_modes.add(raw)
        _LOG.warning("MXNET_GRAPHLINT=%r is not a recognized mode "
                     "(0|warn|error); graphlint stays OFF", raw)
    return None


def lint_bind(symbol, shapes, types, mode, target="bind", mesh=None,
              rules=None, train=True):
    """Bind-time hook used by ``executor.bind`` (single device: memory plan
    only) and ``SPMDStepAdapter`` (real mesh + rules: the full GL4xx/GL5xx
    suite): lint with the concrete bind shapes/dtypes, log findings, and
    under ``error`` raise MXNetError when any error-severity diagnostic
    fires."""
    report = lint(symbol, shapes=shapes, types=types, strict_shapes=True,
                  target=target, mesh=mesh, rules=rules, train=train)
    for d in report:
        if d.severity == Severity.ERROR:
            _LOG.error(d.format())
        elif d.severity == Severity.WARNING:
            _LOG.warning(d.format())
        else:
            _LOG.debug(d.format())
    if mode == "error" and report.errors:
        raise MXNetError(
            "graphlint found %d error(s) at bind (MXNET_GRAPHLINT=error):\n%s"
            % (len(report.errors), report.format(min_severity=Severity.WARNING)))
    return report
