"""Concurrency analyzer — the GL8xx family.

The reference engine serialized every mutation through var dependencies
(PAPER.md: the L2 dependency engine IS the race-safety mechanism). This
port replaced that with free threads — serving batcher, fleet health
pollers and dispatch workers, checkpoint writer, prefetch pumps,
supervisor monitor — plus SPMD collectives whose one hard rule is that
every rank reaches the same collectives in the same order. Neither
property is visible in any Symbol graph; both live in the Python call
sites. So, like GL7xx, this family has a static side and a measured side:

  * **GL801** collective-order divergence: a collective call
    (``allreduce*``, ``allgather*``, barrier, reduce-scatter, ``reform``)
    control-dependent on rank-varying data — the rank itself, a dead-node
    scan, a local clock, a fault-injection outcome, or a caught-exception
    branch. If the condition can differ across ranks, some rank skips (or
    reorders) the rendezvous and the rest hang in it. Reported with the
    provenance chain from the divergent condition to the collective.
  * **GL802** unguarded shared state: an attribute mutated from >=2
    execution contexts — thread entry points discovered from
    ``threading.Thread(target=...)``/``Timer``/pool-``submit`` sites,
    plus the public API surface — with no common lock held on every
    mutating path.
  * **GL803** lock-order inversion: a cycle in the static
    lock-acquisition graph over the named lock attributes.
  * **GL804** blocking-while-holding-lock: a collective, an RPC, or a
    timeout-less ``queue.get()``/``future.result()``/``join()``/
    ``wait()`` reached with a lock held. ``cond.wait()`` on a condition
    backed by the held lock is exempt — wait releases it.
  * **GL805** (measured): ``telemetry.lockwitness`` events from a real
    run under ``MXNET_CONCLINT=witness`` — an observed inversion, or a
    >``MXNET_CONCLINT_HOLD_MS`` hold spanning a dispatch seam.

The analysis is module-local with a bounded call-graph closure: thread
contexts propagate transitively through same-module calls, lock-held sets
inherit two levels of call sites, collective detection follows one level.
That is deep enough for this repo's thread shapes without whole-program
inference — the same budget GL7xx set.

Waivers follow the GL7xx comment convention::

    self._reform()  # graphlint: waive GL801 -- first-write-wins payload

on the finding's line or the line above; ``GL8xx`` waives the family.
CLI: ``tools/graphlint --concurrency [paths] [--format json]
[--witness dump.json]`` (docs/static_analysis.md §GL8xx).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, Report
from .manager import graph_pass
# registration order IS run order: keep the family order stable by
# importing the earlier families first (see dispatch_lint.py)
from . import shape_lint  # noqa: F401
from . import dispatch_lint  # noqa: F401

__all__ = ["lint_concurrency_source", "lint_concurrency_paths",
           "lint_lock_witness", "DEFAULT_SCAN_PATHS"]

# the threaded/distributed surface the repo gate scans by default
DEFAULT_SCAN_PATHS = ("mxnet_tpu/serving", "mxnet_tpu/kvstore.py",
                      "mxnet_tpu/kvstore_bucket.py",
                      "mxnet_tpu/sparse/kvstore_sparse.py",
                      "mxnet_tpu/dist.py", "mxnet_tpu/checkpoint.py",
                      "mxnet_tpu/io.py", "mxnet_tpu/module/elastic.py")

_WAIVE_RE = re.compile(r"#\s*graphlint:\s*waive\s+([A-Za-z0-9, x]+)")

# ---------------------------------------------------------- vocabularies
# cross-rank rendezvous points: every rank must reach these in the same
# order. reform IS a rendezvous (it barriers inside); the digest verifiers
# are allgathers themselves.
_COLLECTIVE_NAMES = frozenset({
    "allreduce", "allreduce_concat", "allreduce_rows", "_allreduce_batch",
    "allgather", "all_gather", "process_allgather", "_allgather_digest",
    "_allgather_union", "make_global_rows",
    "reduce_scatter", "psum", "sync_global_devices",
    "barrier", "_barrier", "wait_at_barrier",
    "broadcast_one_to_all", "_broadcast_rank0",
    "reform", "_verify_across_workers", "_verify_push_round",
})

# calls whose RESULT varies per rank. process_count/num_workers are
# deliberately absent: world size is rank-uniform, so guarding a
# collective on it is the correct idiom, not a divergence.
_RANK_CALLS = {
    "process_index": "the process rank",
    "rank": "the process rank",
    "get_rank": "the process rank",
    "num_dead_nodes": "a dead-node heartbeat scan",
    "get_num_dead_node": "a dead-node heartbeat scan",
    "_scan_heartbeats": "a dead-node heartbeat scan",
    "dead_members": "a dead-node heartbeat scan",
    "poll_pause": "the elastic pause poll (first observer wins)",
    "time": "a local clock",
    "monotonic": "a local clock",
    "perf_counter": "a local clock",
    "fire": "a fault-injection outcome",
    "should_fire": "a fault-injection outcome",
}
# bare names / attribute names that carry rank-varying values
_RANK_NAMES = frozenset({"rank", "orig_rank", "_orig_rank",
                         "process_index", "num_dead", "n_dead",
                         "dead_nodes"})

# attributes assigned one of these constructors hold a concurrency
# primitive, not shared data — lifecycle writes to them are not GL802
_PRIMITIVE_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "Thread", "Timer", "Queue", "SimpleQueue", "LifoQueue",
    "local", "ThreadPoolExecutor", "named_lock", "named_rlock",
    "named_condition"})
_LOCK_CTORS = frozenset({"Lock", "RLock", "allocate_lock", "named_lock",
                         "named_rlock"})
_COND_CTORS = frozenset({"Condition", "named_condition"})

# timeout-less blocking waits (zero-argument form only: dict.get(k),
# "".join(x), thread.join(t) all carry arguments and stay exempt)
_BLOCKING_ZERO_ARG = frozenset({"get", "result", "join", "wait"})
_RPC_HINTS = ("client", "rpc", "stub")


# --------------------------------------------------------------- helpers

def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _self_attr(expr) -> Optional[str]:
    """``self.X`` -> ``"X"``; anything else -> None."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return expr.attr
    return None


def _walk_shallow(node):
    """Walk ``node`` without descending into nested function/class defs."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _load_waivers(text: str) -> Dict[int, set]:
    """line -> waived codes; a waiver covers its line and the line below."""
    waivers: Dict[int, set] = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _WAIVE_RE.search(line)
        if not m:
            continue
        codes = {c.strip().upper() for c in m.group(1).split(",") if c.strip()}
        waivers.setdefault(i, set()).update(codes)
        waivers.setdefault(i + 1, set()).update(codes)
    return waivers


def _is_waived(waivers: Dict[int, set], line: int, code: str) -> bool:
    at = waivers.get(line, ())
    return code in at or "GL8XX" in at


class _Finding:
    """One concurrency-lint site: a Diagnostic plus table metadata."""

    def __init__(self, code, path, line, function, message, fix_hint=None,
                 provenance=None, waived=False):
        self.code = code
        self.path = path
        self.line = line
        self.function = function
        self.message = message
        self.fix_hint = fix_hint
        self.provenance = list(provenance or [])
        self.waived = waived

    @property
    def site(self) -> str:
        return "%s:%d" % (self.path, self.line)

    def to_diagnostic(self) -> Diagnostic:
        msg = self.message
        if self.waived:
            msg += " [waived]"
        return Diagnostic(self.code, msg, node=self.site,
                          fix_hint=self.fix_hint, provenance=self.provenance,
                          pass_name="concurrency_lint",
                          severity="info" if self.waived else None)

    def to_dict(self) -> dict:
        return {"code": self.code, "file": self.path, "line": self.line,
                "function": self.function, "message": self.message,
                "fix_hint": self.fix_hint, "waived": self.waived,
                "provenance": list(self.provenance)}


# ------------------------------------------------------- module modeling

class _Fn:
    """One function/method with the facts the four checks consume."""

    def __init__(self, qualname: str, name: str, cls: Optional[str], node):
        self.qualname = qualname
        self.name = name
        self.cls = cls              # enclosing class name or None
        self.node = node
        self.collectives: List[Tuple[int, str]] = []  # (line, name), shallow
        self.callees: Set[str] = set()                # bare callee names
        for n in _walk_shallow(node):
            if isinstance(n, ast.Call):
                cname = _call_name(n)
                if cname in _COLLECTIVE_NAMES:
                    self.collectives.append((n.lineno, cname))
                if cname:
                    self.callees.add(cname)


class _Module:
    """Module-local model: functions, classes, lock attributes (with
    Condition aliasing), thread entry points, rank-tainted attributes."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.tree = tree
        self.funcs: Dict[str, _Fn] = {}       # qualname AND bare name
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_locks: Dict[str, Dict[str, str]] = {}  # cls -> attr->canon
        self.global_locks: Set[str] = set()
        self.entries: Dict[str, int] = {}     # entry bare name -> line
        self.tainted_attrs: Dict[str, str] = {}  # attr -> reason

        for child in ast.iter_child_nodes(tree):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = _Fn(child.name, child.name, None, child)
                self.funcs[child.name] = fn
            elif isinstance(child, ast.ClassDef):
                self.classes[child.name] = child
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        q = "%s.%s" % (child.name, sub.name)
                        fn = _Fn(q, sub.name, child.name, sub)
                        self.funcs[q] = fn
                        self.funcs.setdefault(sub.name, fn)
            elif isinstance(child, ast.Assign) and \
                    isinstance(child.value, ast.Call) and \
                    _call_name(child.value) in _LOCK_CTORS:
                for tgt in child.targets:
                    if isinstance(tgt, ast.Name):
                        self.global_locks.add(tgt.id)
        self._collect_locks_and_taint()
        self._collect_entries()

    def functions(self):
        """Unique _Fn facts (the bare-name aliases dedup away)."""
        return {id(f): f for f in self.funcs.values()}.values()

    def _collect_locks_and_taint(self):
        for fn in self.functions():
            locks = self.class_locks.setdefault(fn.cls, {}) \
                if fn.cls else None
            for n in _walk_shallow(fn.node):
                if not isinstance(n, ast.Assign):
                    continue
                attrs = [a for t in n.targets
                         for a in [_self_attr(t)] if a]
                if not attrs:
                    continue
                val = n.value
                if isinstance(val, ast.Call) and locks is not None:
                    cname = _call_name(val)
                    if cname in _LOCK_CTORS:
                        for a in attrs:
                            locks[a] = a
                    elif cname in _COND_CTORS:
                        # Condition(self._lock) IS self._lock: alias the
                        # cv attribute to the backing lock so with/wait
                        # analysis sees one lock, not two
                        backing = None
                        args = [arg for arg in val.args]
                        # named_condition("name", self._lock): the lock is
                        # the first non-string positional
                        for arg in args:
                            got = _self_attr(arg)
                            if got:
                                backing = got
                                break
                        for a in attrs:
                            locks[a] = locks.get(backing, backing) \
                                if backing else a
                reasons = _rank_reads(val, {}, {})
                if reasons:
                    for a in attrs:
                        self.tainted_attrs.setdefault(a, reasons[0])

    def _collect_entries(self):
        """Thread entry points: Thread(target=f)/Timer(..., f)/submit(f).
        The target resolves by bare name (``self._loop`` -> ``_loop``)."""
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            cname = _call_name(n)
            target = None
            if cname == "Thread":
                for kw in n.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif cname == "Timer":
                if len(n.args) >= 2:
                    target = n.args[1]
                for kw in n.keywords:
                    if kw.arg == "function":
                        target = kw.value
            elif cname == "submit" and n.args:
                target = n.args[0]
            if target is None:
                continue
            tname = _self_attr(target)
            if tname is None and isinstance(target, ast.Name):
                tname = target.id
            elif tname is None and isinstance(target, ast.Attribute):
                tname = target.attr
            if tname and tname in self.funcs:
                self.entries.setdefault(tname, n.lineno)

    # ------------------------------------------------------ lock identity
    def lock_id(self, expr, cls: Optional[str]):
        """The canonical lock a ``with`` item acquires: ``(cls, attr)``
        for self-attribute locks (Condition attrs alias to their backing
        lock), ``("", name)`` for module-level locks, else None."""
        attr = _self_attr(expr)
        if attr is not None and cls:
            locks = self.class_locks.get(cls, {})
            if attr in locks:
                return (cls, locks[attr])
            return None
        if isinstance(expr, ast.Name) and expr.id in self.global_locks:
            return ("", expr.id)
        return None

    # ------------------------------------------------ context propagation
    def contexts(self) -> Dict[str, Set[str]]:
        """qualname -> execution contexts reaching it: ``thread:<entry>``
        for thread entry points, ``api:<name>`` for public functions and
        methods, propagated transitively through same-module calls."""
        ctx: Dict[str, Set[str]] = {f.qualname: set()
                                    for f in self.functions()}
        for tname in self.entries:
            fn = self.funcs.get(tname)
            if fn is not None:
                ctx[fn.qualname].add("thread:%s" % tname)
        for fn in self.functions():
            if not fn.name.startswith("_") and fn.name not in self.entries:
                ctx[fn.qualname].add("api:%s" % fn.name)
        for _ in range(6):  # bounded closure; call depth here is ~3
            changed = False
            for fn in self.functions():
                mine = ctx[fn.qualname]
                if not mine:
                    continue
                for callee in fn.callees:
                    target = self._resolve(callee, fn.cls)
                    if target is None:
                        continue
                    before = len(ctx[target.qualname])
                    ctx[target.qualname] |= mine
                    changed |= len(ctx[target.qualname]) != before
            if not changed:
                break
        return ctx

    def _resolve(self, bare: str, cls: Optional[str]) -> Optional[_Fn]:
        if cls:
            got = self.funcs.get("%s.%s" % (cls, bare))
            if got is not None:
                return got
        got = self.funcs.get(bare)
        # a bare-name alias may point at another class's method; only
        # trust it for module-level functions or same-class methods
        if got is not None and (got.cls is None or got.cls == cls):
            return got
        return None


def _rank_reads(expr, local_taint: Dict[str, str],
                tainted_attrs: Dict[str, str]) -> List[str]:
    """Provenance lines for every rank-varying read inside ``expr``.
    ``x is None`` comparisons are skipped: presence of a value is
    rank-uniform even when the value (a clock, a scan) is not."""
    out: List[str] = []

    def rec(n):
        if isinstance(n, ast.Compare) and \
                all(isinstance(o, (ast.Is, ast.IsNot)) for o in n.ops):
            return
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            return
        if isinstance(n, ast.Call):
            cname = _call_name(n)
            if cname in _RANK_CALLS:
                out.append("%s() reads %s" % (cname, _RANK_CALLS[cname]))
        elif isinstance(n, ast.Attribute):
            a = _self_attr(n)
            if a is not None and a in tainted_attrs:
                out.append("self.%s carries %s" % (a, tainted_attrs[a]))
            elif n.attr in _RANK_NAMES:
                out.append(".%s reads the process rank" % n.attr)
        elif isinstance(n, ast.Name):
            if n.id in _RANK_NAMES:
                out.append("%r reads the process rank" % n.id)
            elif n.id in local_taint:
                out.append("%r derives from %s" % (n.id, local_taint[n.id]))
        for c in ast.iter_child_nodes(n):
            rec(c)

    rec(expr)
    return out


def _local_taint(fn_node, tainted_attrs: Dict[str, str]) -> Dict[str, str]:
    """Names assigned (transitively, two hops) from rank-varying reads."""
    taint: Dict[str, str] = {}
    for _ in range(2):
        for n in _walk_shallow(fn_node):
            if not isinstance(n, ast.Assign):
                continue
            reasons = _rank_reads(n.value, taint, tainted_attrs)
            if not reasons:
                continue
            for tgt in n.targets:
                if isinstance(tgt, ast.Name):
                    taint.setdefault(tgt.id, reasons[0])
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for t in tgt.elts:
                        if isinstance(t, ast.Name):
                            taint.setdefault(t.id, reasons[0])
    return taint


# ----------------------------------------------------------------- GL801

def _lint_gl801(model: _Module, fn: _Fn, add):
    taint = _local_taint(fn.node, model.tainted_attrs)

    def check_calls(stmt, stack):
        for call in ast.walk(stmt):
            if not isinstance(call, ast.Call):
                continue
            cname = _call_name(call)
            extra = []
            if cname in _COLLECTIVE_NAMES:
                what = "collective %s()" % cname
            else:
                callee = model._resolve(cname, fn.cls) if cname else None
                if callee is None or callee.node is fn.node \
                        or not callee.collectives:
                    continue
                cline, ccall = callee.collectives[0]
                what = "%s(), which performs collective %s() at line %d" \
                    % (cname, ccall, cline)
                extra = ["%s() reaches %s() at line %d"
                         % (callee.qualname, ccall, cline)]
            prov = []
            for line, kind, reasons in stack:
                prov.append("%s at line %d is rank-varying: %s"
                            % (kind, line, reasons[0]))
            add("GL801", call.lineno, fn.qualname,
                "%s is control-dependent on rank-varying data (%s at "
                "line %d): ranks that branch differently skip or reorder "
                "the rendezvous and the rest deadlock in it"
                % (what, stack[-1][1], stack[-1][0]),
                fix_hint="hoist the collective out of the rank-varying "
                "branch, or make the branch rank-uniform first (agree on "
                "the value via the coordination KV / an allgather)",
                provenance=prov + extra)

    def visit(stmts, stack):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            if isinstance(st, (ast.If, ast.While)):
                reasons = _rank_reads(st.test, taint, model.tainted_attrs)
                entry = [(st.lineno, "branch condition", reasons)] \
                    if reasons else []
                visit(st.body, stack + entry)
                visit(st.orelse, stack + entry)
            elif isinstance(st, ast.For):
                reasons = _rank_reads(st.iter, taint, model.tainted_attrs)
                entry = [(st.lineno, "loop iterable", reasons)] \
                    if reasons else []
                visit(st.body, stack + entry)
                visit(st.orelse, stack + entry)
            elif isinstance(st, ast.Try):
                visit(st.body, stack)
                for h in st.handlers:
                    entry = [(h.lineno, "except handler",
                              ["which rank raises (and what) is "
                               "runtime-local"])]
                    visit(h.body, stack + entry)
                visit(st.orelse, stack)
                visit(st.finalbody, stack)
            elif isinstance(st, ast.With):
                visit(st.body, stack)
            else:
                if stack:
                    check_calls(st, stack)

    visit(fn.node.body, [])


# ---------------------------------------------- held-lock walking (3/4)

def _iter_held(stmts, held: frozenset, lock_of):
    """Yield ``(kind, node, held, acquired)`` for every statement with the
    lock set lexically held there. ``kind`` is ``"with"`` (acquired =
    locks its items take), else ``"stmt"``."""
    for st in stmts:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        if isinstance(st, ast.With):
            acquired = []
            for item in st.items:
                lid = lock_of(item.context_expr)
                if lid is not None:
                    acquired.append(lid)
            yield ("with", st, held, acquired)
            yield from _iter_held(st.body, held | frozenset(acquired),
                                  lock_of)
        elif isinstance(st, (ast.If, ast.While)):
            yield ("stmt", st.test, held, None)
            yield from _iter_held(st.body, held, lock_of)
            yield from _iter_held(st.orelse, held, lock_of)
        elif isinstance(st, ast.For):
            yield ("stmt", st.iter, held, None)
            yield from _iter_held(st.body, held, lock_of)
            yield from _iter_held(st.orelse, held, lock_of)
        elif isinstance(st, ast.Try):
            yield from _iter_held(st.body, held, lock_of)
            for h in st.handlers:
                yield from _iter_held(h.body, held, lock_of)
            yield from _iter_held(st.orelse, held, lock_of)
            yield from _iter_held(st.finalbody, held, lock_of)
        else:
            yield ("stmt", st, held, None)


def _fn_lock_facts(model: _Module, fn: _Fn):
    """(acquire_edges, call_sites, blocking_sites, mutation_sites,
    acquired_locks) for one function, from the lexical held-walk."""
    lock_of = lambda e: model.lock_id(e, fn.cls)  # noqa: E731
    edges = []       # (held_lock, acquired_lock, line)
    calls = []       # (bare_name, line, held)
    mutations = []   # (attr, line, held)
    acquired = set()
    for kind, node, held, got in _iter_held(fn.node.body, frozenset(),
                                            lock_of):
        if kind == "with":
            for lid in got:
                acquired.add(lid)
                for h in held:
                    if h != lid:
                        edges.append((h, lid, node.lineno))
            continue
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                cname = _call_name(n)
                if cname:
                    calls.append((cname, n, held))
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                val_ctor = _call_name(n.value) \
                    if isinstance(n.value, ast.Call) else None
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    attr = _self_attr(base)
                    if attr is None:
                        continue
                    if val_ctor in _PRIMITIVE_CTORS:
                        continue
                    mutations.append((attr, n.lineno, held))
    return edges, calls, mutations, acquired


_LIFECYCLE = frozenset({"__init__", "__new__", "__del__", "__enter__",
                        "__exit__"})


def _lint_module(model: _Module, add):
    """GL802/GL803/GL804 need whole-module facts; GL801 is per-function."""
    facts = {}
    for fn in model.functions():
        facts[fn.qualname] = _fn_lock_facts(model, fn)
        _lint_gl801(model, fn, add)

    # -- held-set inheritance: two rounds of call-site intersection -------
    inherited: Dict[str, frozenset] = {q: frozenset() for q in facts}
    for _ in range(2):
        nxt = {}
        for fn in model.functions():
            sites = []
            for caller in model.functions():
                if caller.node is fn.node:
                    continue
                _e, calls, _m, _a = facts[caller.qualname]
                for cname, _node, held in calls:
                    target = model._resolve(cname, caller.cls)
                    if target is not None and target.node is fn.node:
                        sites.append(frozenset(held)
                                     | inherited[caller.qualname])
            if sites:
                common = sites[0]
                for s in sites[1:]:
                    common &= s
                nxt[fn.qualname] = common
            else:
                nxt[fn.qualname] = frozenset()
        inherited = nxt

    # -- GL803: cycles in the acquisition graph ---------------------------
    graph: Dict[tuple, Dict[tuple, Tuple[str, int]]] = {}

    def edge(a, b, fn, line):
        graph.setdefault(a, {}).setdefault(b, (fn, line))

    for fn in model.functions():
        edges, calls, _m, _a = facts[fn.qualname]
        base = inherited[fn.qualname]
        for h, lid, line in edges:
            edge(h, lid, fn.qualname, line)
        for h in base:
            for _hh, lid, line in edges:
                if lid != h:
                    edge(h, lid, fn.qualname, line)
        # one level: calling a method that acquires L while holding H
        for cname, node, held in calls:
            target = model._resolve(cname, fn.cls)
            if target is None or target.node is fn.node:
                continue
            _te, _tc, _tm_, tacq = facts[target.qualname]
            for h in frozenset(held) | base:
                for lid in tacq:
                    if lid != h:
                        edge(h, lid, fn.qualname, node.lineno)

    reported_pairs = set()
    for a in sorted(graph):
        for b in sorted(graph[a]):
            if a in graph.get(b, ()):
                pair = frozenset((a, b))
                if pair in reported_pairs:
                    continue
                reported_pairs.add(pair)
                fn_ab, line_ab = graph[a][b]
                fn_ba, line_ba = graph[b][a]
                fmt = lambda lid: ("%s.%s" % lid if lid[0]  # noqa: E731
                                   else lid[1])
                add("GL803", max(line_ab, line_ba),
                    fn_ab if line_ab >= line_ba else fn_ba,
                    "lock-order inversion: %s acquired before %s (line %d "
                    "in %s) but %s before %s (line %d in %s) — two threads "
                    "taking the paths concurrently deadlock"
                    % (fmt(a), fmt(b), line_ab, fn_ab,
                       fmt(b), fmt(a), line_ba, fn_ba),
                    fix_hint="pick one global order for these locks and "
                    "re-nest the laggard path (or collapse to one lock)",
                    provenance=["%s -> %s at %s:%d" % (fmt(a), fmt(b),
                                                       fn_ab, line_ab),
                                "%s -> %s at %s:%d" % (fmt(b), fmt(a),
                                                       fn_ba, line_ba)])

    # -- GL804: blocking with a lock held ---------------------------------
    for fn in model.functions():
        _e, calls, _m, _a = facts[fn.qualname]
        base = inherited[fn.qualname]
        for cname, node, held in calls:
            eff = frozenset(held) | base
            if not eff:
                continue
            blocking = None
            if cname in _COLLECTIVE_NAMES:
                blocking = "collective %s()" % cname
            elif cname == "blocking_key_value_get":
                blocking = "coordination-service RPC %s()" % cname
            elif cname in _BLOCKING_ZERO_ARG and not node.args \
                    and not node.keywords:
                recv = node.func.value \
                    if isinstance(node.func, ast.Attribute) else None
                rattr = _self_attr(recv) if recv is not None else None
                if cname == "wait" and rattr is not None and fn.cls:
                    locks = model.class_locks.get(fn.cls, {})
                    canon = locks.get(rattr)
                    if canon is not None and (fn.cls, canon) in eff:
                        continue  # cond.wait() releases the held lock
                if isinstance(recv, ast.Name) and recv.id == "self" and \
                        model._resolve(cname, fn.cls) is not None:
                    continue  # self.wait() etc is a method call, not a
                    # primitive wait — the callee's own sites are linted
                blocking = "timeout-less %s()" % cname
            elif cname == "call" and isinstance(node.func, ast.Attribute):
                parts = []
                cur = node.func.value
                while isinstance(cur, ast.Attribute):
                    parts.append(cur.attr.lower())
                    cur = cur.value
                if isinstance(cur, ast.Name):
                    parts.append(cur.id.lower())
                if any(h in p for p in parts for h in _RPC_HINTS):
                    blocking = "RPC %s()" % ast.unparse(node.func)
            if blocking is None:
                continue
            fmt = lambda lid: ("%s.%s" % lid if lid[0]  # noqa: E731
                               else lid[1])
            add("GL804", node.lineno, fn.qualname,
                "%s reached while holding %s: every other thread needing "
                "the lock stalls behind an unbounded wait"
                % (blocking, ", ".join(sorted(fmt(h) for h in eff))),
                fix_hint="move the blocking call outside the lock, or "
                "bound it with a timeout/deadline knob and handle expiry",
                provenance=["lock(s) held here: %s"
                            % ", ".join(sorted(fmt(h) for h in eff))])

    # -- GL802: shared attributes without a common lock -------------------
    ctx = model.contexts()
    by_attr: Dict[tuple, List[tuple]] = {}
    for fn in model.functions():
        if fn.cls is None or fn.name in _LIFECYCLE:
            continue
        _e, _c, mutations, _a = facts[fn.qualname]
        base = inherited[fn.qualname]
        for attr, line, held in mutations:
            locks = model.class_locks.get(fn.cls, {})
            if attr in locks or attr in set(locks.values()):
                continue
            by_attr.setdefault((fn.cls, attr), []).append(
                (line, fn, frozenset(held) | base))
    for (cls, attr), sites in sorted(by_attr.items()):
        union_ctx: Set[str] = set()
        per_site = []
        for line, fn, eff in sites:
            fctx = ctx.get(fn.qualname, set())
            if not fctx:
                continue  # unreachable from any entry/API: not shared
            union_ctx |= fctx
            per_site.append((line, fn, eff, fctx))
        if len(union_ctx) < 2 or \
                not any(c.startswith("thread:") for c in union_ctx):
            continue
        common = per_site[0][2]
        for _l, _f, eff, _c2 in per_site[1:]:
            common &= eff
        if common:
            continue
        fmt = lambda lid: ("%s.%s" % lid if lid[0] else lid[1])  # noqa: E731
        per_site.sort(key=lambda s: s[0])
        worst = next((s for s in per_site if not s[2]), per_site[0])
        add("GL802", worst[0], worst[1].qualname,
            "self.%s is mutated from %d contexts (%s) with no common lock "
            "on every mutating path" % (attr, len(union_ctx),
                                        ", ".join(sorted(union_ctx))),
            fix_hint="guard every mutating path with one named lock "
            "(telemetry.named_lock) or confine the attribute to a single "
            "thread",
            provenance=["line %d in %s holds {%s}; reachable from %s"
                        % (line, fn.qualname,
                           ", ".join(sorted(fmt(h) for h in eff)) or "-",
                           ", ".join(sorted(fctx)))
                        for line, fn, eff, fctx in per_site[:6]])


# ------------------------------------------------------------ public API

def lint_concurrency_source(path: str, text: Optional[str] = None
                            ) -> List[_Finding]:
    """Static GL801-GL804 over one Python source file."""
    if text is None:
        with open(path) as f:
            text = f.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as exc:
        return [_Finding("GL804", path, exc.lineno or 1, "<module>",
                         "unparseable source: %s" % exc, waived=False)]
    waivers = _load_waivers(text)
    model = _Module(path, tree)
    findings: List[_Finding] = []
    seen = set()

    def add(code, line, function, message, fix_hint=None, provenance=None):
        key = (code, line)
        if key in seen:
            return
        seen.add(key)
        findings.append(_Finding(
            code, path, line, function, message, fix_hint=fix_hint,
            provenance=provenance, waived=_is_waived(waivers, line, code)))

    _lint_module(model, add)
    findings.sort(key=lambda f: (f.line, f.code))
    return findings


def _iter_py_files(paths):
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        elif os.path.exists(p):
            yield p
        else:
            raise OSError("concurrency-lint path does not exist: %s" % p)


def lint_concurrency_paths(paths=None, root: Optional[str] = None
                           ) -> Tuple[Report, List[dict]]:
    """Run the static concurrency lint over ``paths`` (files or
    directories; default ``DEFAULT_SCAN_PATHS`` resolved against ``root``
    or the repo checkout this package sits in).

    Returns ``(Report, site rows)``; waived findings are severity-info in
    the report (they never fail a run) and ``"waived": true`` in rows."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if paths is None:
        paths = [os.path.join(root, p) for p in DEFAULT_SCAN_PATHS]
        paths = [p for p in paths if os.path.exists(p)]
    report = Report(target="concurrency")
    sites: List[dict] = []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path, root) if os.path.isabs(path) else path
        for f in lint_concurrency_source(path):
            f.path = rel
            report.add(f.to_diagnostic())
            sites.append(f.to_dict())
    return report, sites


# ----------------------------------------------------- measured: GL805

def lint_lock_witness(witness: Optional[dict]) -> List[Diagnostic]:
    """GL805 over a ``telemetry.lockwitness.witness_report()`` dict (or
    the ``otherData.lock_witness`` block of a chrome dump): one finding
    per witnessed inversion, one per >threshold hold that crossed a
    dispatch seam. Long holds that never crossed a seam stay in the
    contention table but are not findings — holding a lock through host
    work is legal; holding it across device dispatch serializes the
    pipeline."""
    diags: List[Diagnostic] = []
    if not witness:
        return diags
    for ev in witness.get("events", ()):
        kind = ev.get("kind")
        if kind == "inversion":
            diags.append(Diagnostic(
                "GL805",
                "witnessed lock-order inversion: thread %r acquired %r "
                "then %r after the reverse order (%s) was taken %d "
                "time(s) — a concurrent interleaving of the two paths "
                "deadlocks"
                % (ev.get("thread"), ev.get("first"), ev.get("then"),
                   ev.get("prior_order"), ev.get("prior_count", 1)),
                node="%s<->%s" % (ev.get("first"), ev.get("then")),
                fix_hint="pick one global acquisition order for these "
                "locks (see the static GL803 sites for the paths)",
                pass_name="concurrency_lint"))
        elif kind == "long_hold" and ev.get("dispatch_seam"):
            diags.append(Diagnostic(
                "GL805",
                "witnessed long hold: %r held %.1f ms (threshold %.0f ms) "
                "across a dispatch seam on thread %r — the lock sat "
                "across device-dispatch work, stalling every contender"
                % (ev.get("lock"), ev.get("hold_ms", 0.0),
                   ev.get("threshold_ms", 0.0), ev.get("thread")),
                node=ev.get("lock"),
                fix_hint="shrink the critical section: snapshot under the "
                "lock, dispatch outside it",
                pass_name="concurrency_lint"))
    return diags


@graph_pass("concurrency_lint")
def concurrency_lint_pass(ctx):
    """Bind-time face of the family: when the process is witnessing
    (``MXNET_CONCLINT=witness``), surface any GL805 the witness has
    recorded so far. The static GL801-804 checks are source-level and run
    through ``graphlint --concurrency`` / the CI repo gate instead."""
    from ..telemetry import lockwitness

    if not lockwitness.witnessing():
        return []
    return lint_lock_witness(lockwitness.witness_report())
