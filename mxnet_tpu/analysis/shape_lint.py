"""Shape/dtype propagation lint (GL001–GL006).

Re-runs the executor's inference symbolically — same backward parameter
rules (``ops.infer_meta.backward_shape_rule``), same per-node abstract
evaluation (``symbol._eval_node_shape`` / ``jax.eval_shape``) — but with
per-node error recovery: a node that cannot be inferred becomes a
diagnostic carrying the full producer provenance chain, and the walk
continues so ONE lint run reports EVERY broken node, where ``bind`` stops
at the first JAX traceback.

Codes:
  GL001  op-level inference failed (eval_shape raised) — unbindable node
  GL002  argument shape still underdetermined under full hints
  GL003  declared ``__shape__``/hint conflicts with the inferred shape
  GL004  mixed-dtype inputs silently promoted (per infer_meta dtype_policy)
  GL005  duplicate node names (bind-by-dict / output_dict collide)
  GL006  input rank violates the op's declared rank constraints
"""
from __future__ import annotations

import numpy as np

from ..base import np_dtype
from ..ops.infer_meta import backward_shape_rule, get_meta
from ..symbol import _eval_node_shape, _aux_positions, _freeze, _parse_shape_attr
from .diagnostics import Diagnostic
from .manager import GraphContext, graph_pass

__all__ = ["propagate", "shape_dtype_lint"]


def _short_exc(exc) -> str:
    """First informative line of an exception, without the traceback."""
    msg = str(exc).strip()
    for line in msg.splitlines():
        line = line.strip()
        if line:
            return line[:300]
    return type(exc).__name__


def propagate(ctx: GraphContext):
    """Fill ctx.entry_shape/entry_dtype/var_shape/var_dtype node by node,
    yielding diagnostics instead of raising. Mirrors ``symbol._infer_impl``
    (the executor's single inference pass) with error recovery."""
    diags = []
    for node in ctx.topo:
        if not node.is_variable:
            continue
        sh = ctx.shape_hints.get(node.name)
        declared = None
        if "__shape__" in node.attrs:
            declared = _parse_shape_attr(node.attrs["__shape__"])
        if sh is not None and declared is not None and tuple(sh) != tuple(declared):
            diags.append(Diagnostic(
                "GL003",
                "hinted shape %s conflicts with declared __shape__ %s"
                % (tuple(sh), tuple(declared)),
                node=node.name,
                fix_hint="drop the Variable(shape=...) declaration or pass a "
                         "matching hint",
            ))
        if sh is None:
            sh = declared
        dt = ctx.type_hints.get(node.name)
        if dt is None and "__dtype__" in node.attrs:
            dt = np_dtype(node.attrs["__dtype__"])
        ctx.var_shape[node.name] = tuple(sh) if sh is not None else None
        ctx.var_dtype[node.name] = np.dtype(dt) if dt is not None else None
        ctx.entry_shape[(id(node), 0)] = ctx.var_shape[node.name]
        ctx.entry_dtype[(id(node), 0)] = ctx.var_dtype[node.name]

    for node in ctx.topo:
        if node.is_variable:
            continue
        try:
            parsed = node.parsed_attrs()
        except Exception as exc:
            diags.append(Diagnostic(
                "GL001", "attribute parsing failed: %s" % _short_exc(exc),
                node=node.name, op=node.op,
                provenance=ctx.provenance(node)))
            ctx.blocked[id(node)] = "bad attributes"
            _mark_unknown(ctx, node)
            continue
        in_entries = [(id(n), i) for n, i in node.inputs]
        in_shapes = [ctx.entry_shape.get(e) for e in in_entries]

        meta = get_meta(node.op)
        try:
            slots = node.opdef().input_names(parsed) + node.opdef().aux_names(parsed)
        except Exception:
            slots = []

        # Backward parameter-shape rule fills variable inputs (FC weight...).
        # Declared param slots are masked so the rule re-deduces them: a
        # mismatch between declaration and deduction is then a precise GL003
        # at the variable, not a cryptic GL001 two nodes downstream.
        rule = backward_shape_rule(node.op)
        conflict = False
        if rule is not None:
            masked, remasked = [], []
            for i, ((inp, _), s) in enumerate(zip(node.inputs, in_shapes)):
                slot = slots[i] if i < len(slots) else None
                m = (inp.is_variable and s is not None
                     and slot in meta.param_slots)
                masked.append(None if m else s)
                remasked.append(m)
            try:
                filled = rule(parsed, list(masked))
            except Exception as exc:
                filled = masked
                diags.append(Diagnostic(
                    "GL001",
                    "backward shape rule failed: %s" % _short_exc(exc),
                    node=node.name, op=node.op,
                    provenance=ctx.provenance(node)))
            for (inp, out_i), old, new, was_masked in zip(
                    node.inputs, in_shapes, filled, remasked):
                if new is None:
                    continue
                new = tuple(int(x) for x in new)
                if old is None:
                    ctx.entry_shape[(id(inp), out_i)] = new
                    if inp.is_variable:
                        ctx.var_shape[inp.name] = new
                elif was_masked and tuple(old) != new:
                    diags.append(Diagnostic(
                        "GL003",
                        "%s (%s) requires shape %s for %r, conflicting with "
                        "its declared shape %s"
                        % (node.name, node.op, new, inp.name, tuple(old)),
                        node=inp.name,
                        provenance=ctx.provenance(node, depth=2, max_lines=4),
                        fix_hint="fix the Variable(shape=...) declaration or "
                                 "the layer configuration feeding %s"
                                 % node.name,
                    ))
                    conflict = True
            in_shapes = [ctx.entry_shape.get(e) for e in in_entries]
        if conflict:
            ctx.blocked[id(node)] = "declared/deduced shape conflict"
            _mark_unknown(ctx, node)
            continue

        in_dtypes = [ctx.entry_dtype.get(e) for e in in_entries]

        # rank constraints from infer_meta: a precise GL006 beats the
        # eval_shape crash the bad rank would cause two lines later
        rank_bad = False
        if meta.input_ranks:
            for slot, (inp, oi), sh in zip(slots, node.inputs, in_shapes):
                lohi = meta.input_ranks.get(slot)
                if lohi is None or sh is None:
                    continue
                lo, hi = lohi
                if not (lo <= len(sh) <= hi):
                    want = ("rank %d" % lo) if lo == hi else "rank %d..%s" % (lo, hi)
                    diags.append(Diagnostic(
                        "GL006",
                        "input %r has rank %d (shape %s); %s requires %s"
                        % (slot, len(sh), tuple(sh), node.op, want),
                        node=node.name, op=node.op,
                        provenance=ctx.provenance(node),
                        fix_hint="reshape/expand the %r input or fix the "
                                 "producing layer" % slot,
                    ))
                    rank_bad = True
        if rank_bad:
            ctx.blocked[id(node)] = "rank constraint violated"
            _mark_unknown(ctx, node)
            continue

        if any(s is None for s in in_shapes):
            missing = sorted({
                inp.name for (inp, _), s in zip(node.inputs, in_shapes)
                if s is None and inp.is_variable
            })
            blocked_by = sorted({
                inp.name for (inp, _), s in zip(node.inputs, in_shapes)
                if s is None and not inp.is_variable
            })
            ctx.blocked[id(node)] = (
                "unknown input shapes: vars %s%s"
                % (missing, (" via %s" % blocked_by) if blocked_by else ""))
            ctx.blocked_vars[id(node)] = set(missing)
            _mark_unknown(ctx, node, dtype=_promote(in_dtypes))
            continue

        # GL004: ops that numpy-promote see mixed input dtypes
        known = [d for d in in_dtypes if d is not None]
        if meta.dtype_policy == "promote" and len({d.name for d in known}) > 1:
            promoted = np.result_type(*known)
            diags.append(Diagnostic(
                "GL004",
                "inputs have mixed dtypes %s; %s silently promotes to %s"
                % (sorted({d.name for d in known}), node.op, promoted.name),
                node=node.name, op=node.op,
                provenance=ctx.provenance(node, depth=2, max_lines=4),
                fix_hint="insert an explicit Cast (or declare the Variable "
                         "dtype) so the widening is intentional",
            ))
        filled_dtypes = [np.dtype(np.float32) if d is None else d for d in in_dtypes]
        for (inp, _), d in zip(node.inputs, filled_dtypes):
            if inp.is_variable and ctx.var_dtype.get(inp.name) is None:
                ctx.var_dtype[inp.name] = d
                ctx.entry_dtype[(id(inp), 0)] = d

        try:
            out_structs = _eval_node_shape(
                node.op, _freeze(parsed), tuple(in_shapes),
                tuple(str(d) for d in filled_dtypes), _aux_positions(node))
        except Exception as exc:
            diags.append(Diagnostic(
                "GL001",
                "shape/dtype inference failed: %s" % _short_exc(exc),
                node=node.name, op=node.op,
                provenance=ctx.provenance(node),
                fix_hint="the op rejected these input shapes; the chain above "
                         "shows where each one came from",
            ))
            ctx.blocked[id(node)] = "op inference raised"
            _mark_unknown(ctx, node)
            continue
        for i, st in enumerate(out_structs[: node.num_outputs()]):
            ctx.entry_shape[(id(node), i)] = tuple(st[0])
            ctx.entry_dtype[(id(node), i)] = np.dtype(st[1])
    return diags


def _promote(in_dtypes):
    known = [d for d in in_dtypes if d is not None]
    if not known:
        return None
    return np.dtype(np.result_type(*known))


def _mark_unknown(ctx: GraphContext, node, dtype=None):
    for i in range(node.num_outputs()):
        ctx.entry_shape[(id(node), i)] = None
        ctx.entry_dtype[(id(node), i)] = dtype


@graph_pass("shape_lint")
def shape_dtype_lint(ctx: GraphContext):
    diags = list(propagate(ctx))

    # GL005: duplicate names. Two distinct variable nodes with one name make
    # bind-by-dict ambiguous (error); duplicate op-node names corrupt
    # output_dict/attr_dict lookups (warning).
    seen_vars, seen_ops = {}, {}
    for node in ctx.topo:
        table = seen_vars if node.is_variable else seen_ops
        if node.name in table:
            kind = "variable" if node.is_variable else "op node"
            diags.append(Diagnostic(
                "GL005",
                "duplicate %s name %r (also used by a %s)"
                % (kind, node.name,
                   seen_vars.get(node.name) or seen_ops.get(node.name)),
                node=node.name, op=node.op,
                severity="error" if node.is_variable else "warning",
                fix_hint="pass name=... to the colliding layer or rename the "
                         "Variable",
            ))
        else:
            table[node.name] = "variable" if node.is_variable else node.op
    # a name used by BOTH a variable and an op node is also a collision
    for name in set(seen_vars) & set(seen_ops):
        diags.append(Diagnostic(
            "GL005",
            "name %r is used by both a variable and an op node" % name,
            node=name, severity="warning",
            fix_hint="rename one of them",
        ))

    # GL002: under full hints the graph must bind — leftover unknowns are
    # errors, attributed to the nodes they blocked
    if ctx.strict_shapes:
        for node in ctx.arg_nodes:
            if ctx.var_shape.get(node.name) is None:
                blockers = [
                    "%s (%s): %s" % (nd.name, nd.op, ctx.blocked.get(id(nd)))
                    for nd in ctx.topo
                    if not nd.is_variable
                    and node.name in ctx.blocked_vars.get(id(nd), ())
                ][:4]
                diags.append(Diagnostic(
                    "GL002",
                    "argument %r has no shape after applying all hints and "
                    "backward rules" % node.name,
                    node=node.name,
                    provenance=blockers,
                    fix_hint="pass %s=<shape> to bind/infer_shape, or declare "
                             "Variable(shape=...)" % node.name,
                ))
    return diags
