"""Engine race analysis (GL101–GL105).

The engine's correctness contract is entirely in the ``const_vars`` /
``mutable_vars`` sets callers pass to ``engine.push`` (reference:
include/mxnet/engine.h Push) — nothing checks that callers declare them
honestly or coherently. Two layers of defense:

* **Static schedule analysis** — ``RecordingEngine`` wraps any engine and
  records every ``new_variable`` / ``push`` / ``wait_for_var`` with the
  caller's file:line. ``analyze_trace`` then flags declaration hazards:

    GL101  a var in both const_vars and mutable_vars of one push (the
           reference's CheckDuplicate rejects this outright; our engines
           resolve it as a write, which readers of the code won't expect)
    GL102  wait_for_var on a var no push in the whole trace ever writes
    GL103  the same var twice in one push's mutable_vars (a write-write
           declared inside a single op)
    GL104  a const read with no preceding write — either never written
           (reads an uninitialized slot) or first written by a LATER push
           (the read does NOT wait for that write: unordered read-write)

* **Runtime assertion shim** (``assert_discipline=True``) — for the
  pure-Python backend, each pushed fn is bracketed with entry/exit
  bookkeeping that checks the var discipline the moment the op actually
  runs: no two writers overlap on a var, no reader overlaps a writer.
  A violation is recorded (GL105) and also raised into the engine's error
  channel. This is how ``tests/test_graphlint.py`` proves the shipped
  ``_PythonThreadedEngine`` honest — and catches a future broken one.
"""
from __future__ import annotations

import threading
import traceback

from ..base import MXNetError
from ..engine import Engine
from .diagnostics import Diagnostic, Report

__all__ = ["PushRecord", "ScheduleTrace", "RecordingEngine", "analyze_trace"]


def _caller_site():
    """file:line of the frame that called into the engine facade."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        fname = frame.filename
        if "analysis/engine_race" not in fname.replace("\\", "/"):
            return "%s:%d" % (fname, frame.lineno)
    return "<unknown>"


class PushRecord:
    __slots__ = ("seq", "const_vars", "mutable_vars", "where")

    def __init__(self, seq, const_vars, mutable_vars, where):
        self.seq = seq
        self.const_vars = tuple(const_vars)
        self.mutable_vars = tuple(mutable_vars)
        self.where = where

    def __repr__(self):
        return ("<push #%d const=%s mutable=%s @ %s>"
                % (self.seq, list(self.const_vars), list(self.mutable_vars),
                   self.where))


class ScheduleTrace:
    """Everything observed through one RecordingEngine."""

    def __init__(self):
        self.created = []          # var ids from new_variable, in order
        self.pushes = []           # PushRecord, in push order
        self.waits = []            # (seq, var, where)
        self.violations = []       # runtime shim findings (strings)
        self._seq = 0
        self._lock = threading.Lock()

    def next_seq(self):
        with self._lock:
            self._seq += 1
            return self._seq


class RecordingEngine(Engine):
    """Engine proxy: records the schedule; optionally asserts the var
    discipline at op execution time (the pure-Python-backend shim)."""

    def __init__(self, inner: Engine, assert_discipline: bool = False):
        self.inner = inner
        self.trace = ScheduleTrace()
        self.assert_discipline = assert_discipline
        self._run_lock = threading.Lock()
        self._running_readers = {}   # var -> count
        self._running_writers = {}   # var -> count (should never exceed 1)

    # ------------------------------------------------------------ facade
    def new_variable(self):
        v = self.inner.new_variable()
        self.trace.created.append(v)
        return v

    def push(self, fn, const_vars=(), mutable_vars=()):
        rec = PushRecord(self.trace.next_seq(), const_vars, mutable_vars,
                         _caller_site())
        self.trace.pushes.append(rec)
        if self.assert_discipline:
            fn = self._shimmed(fn, rec)
        return self.inner.push(fn, const_vars=const_vars,
                               mutable_vars=mutable_vars)

    def wait_for_var(self, var):
        self.trace.waits.append((self.trace.next_seq(), var, _caller_site()))
        return self.inner.wait_for_var(var)

    def wait_for_all(self):
        return self.inner.wait_for_all()

    # -------------------------------------------------------------- shim
    def _shimmed(self, fn, rec: PushRecord):
        # overlap of declared sets within one push is resolved write-wins,
        # matching the engines' own dedup
        muts = tuple(dict.fromkeys(rec.mutable_vars))
        consts = tuple(v for v in dict.fromkeys(rec.const_vars)
                       if v not in muts)

        def run():
            bad = []
            with self._run_lock:
                for v in muts:
                    if self._running_writers.get(v):
                        bad.append("write-write overlap on var %r" % v)
                    if self._running_readers.get(v):
                        bad.append("write overlaps %d running reader(s) on "
                                   "var %r" % (self._running_readers[v], v))
                for v in consts:
                    if self._running_writers.get(v):
                        bad.append("read overlaps a running writer on var %r"
                                   % v)
                for v in muts:
                    self._running_writers[v] = self._running_writers.get(v, 0) + 1
                for v in consts:
                    self._running_readers[v] = self._running_readers.get(v, 0) + 1
                if bad:
                    self.trace.violations.extend(
                        "%s (push #%d from %s)" % (b, rec.seq, rec.where)
                        for b in bad)
            try:
                if bad:
                    raise MXNetError(
                        "engine discipline violated: %s" % "; ".join(bad))
                return fn()
            finally:
                with self._run_lock:
                    for v in muts:
                        self._running_writers[v] -= 1
                    for v in consts:
                        self._running_readers[v] -= 1

        return run


def analyze_trace(trace: ScheduleTrace, target: str = "engine-schedule") -> Report:
    """Static hazard analysis over a recorded schedule."""
    report = Report(target=target)
    first_write = {}   # var -> seq of first push that mutates it
    for rec in trace.pushes:
        for v in rec.mutable_vars:
            first_write.setdefault(v, rec.seq)

    for rec in trace.pushes:
        overlap = sorted(set(rec.const_vars) & set(rec.mutable_vars))
        if overlap:
            report.add(Diagnostic(
                "GL101",
                "push #%d declares var(s) %s as BOTH const and mutable; the "
                "engine resolves this as a write, serializing what the "
                "const_vars entry promises can run concurrently"
                % (rec.seq, overlap),
                node=rec.where, pass_name="engine_race",
                fix_hint="declare each var exactly once: mutable if the op "
                         "writes it, const otherwise",
            ))
        dups = sorted({v for v in rec.mutable_vars
                       if rec.mutable_vars.count(v) > 1})
        if dups:
            report.add(Diagnostic(
                "GL103",
                "push #%d lists var(s) %s more than once in mutable_vars — a "
                "write-write hazard declared within a single op"
                % (rec.seq, dups),
                node=rec.where, pass_name="engine_race",
                fix_hint="deduplicate the mutable_vars list at the call site",
            ))
        for v in dict.fromkeys(rec.const_vars):
            if v in rec.mutable_vars:
                continue  # GL101 already covers the overlap
            fw = first_write.get(v)
            if fw is None:
                report.add(Diagnostic(
                    "GL104",
                    "push #%d reads var %r which NO push in this schedule "
                    "ever writes — the read is ordered against nothing and "
                    "sees whatever the initial state is" % (rec.seq, v),
                    node=rec.where, pass_name="engine_race",
                    fix_hint="either drop the var from const_vars or add the "
                             "producing push",
                ))
            elif fw > rec.seq:
                report.add(Diagnostic(
                    "GL104",
                    "push #%d reads var %r whose FIRST write is pushed later "
                    "(push #%d): the read does not wait for that write — "
                    "unordered read-write" % (rec.seq, v, fw),
                    node=rec.where, pass_name="engine_race",
                    fix_hint="push the writer before the reader; engine "
                             "ordering is push order, per var",
                ))

    written = set(first_write)
    for seq, var, where in trace.waits:
        if var not in written:
            report.add(Diagnostic(
                "GL102",
                "wait_for_var(%r) at seq %d, but no push in this schedule "
                "writes that var — the wait can only drain pending READERS "
                "of it; if this wait was meant to order against produced "
                "data, the producing push is missing (and on a var the "
                "engine never issued, wait_for_var raises)" % (var, seq),
                node=where, pass_name="engine_race",
                fix_hint="if the wait exists to drain readers, it is "
                         "working as intended and this finding can be "
                         "ignored; otherwise add (or wait on) the push that "
                         "actually mutates the var",
            ))

    for v in trace.violations:
        report.add(Diagnostic(
            "GL105",
            "runtime discipline violation: %s" % v,
            pass_name="engine_race",
            fix_hint="the engine executed ops concurrently that its var "
                     "declarations forbid — this is an engine bug, not a "
                     "caller bug",
        ))
    return report
