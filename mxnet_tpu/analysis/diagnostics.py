"""Structured diagnostics for the static-analysis subsystem.

The reference framework surfaces graph errors through nnvm pass exceptions
(InferShape failures are a C++ throw with the node name baked into the
message); XLA surfaces them as multi-page tracebacks from deep inside jit
tracing. Both lose the *graph-level* story. A ``Diagnostic`` keeps it:
every finding has a stable code (``GL001`` ...), a severity, the node it
anchors to, a one-line message, an optional fix hint, and a provenance
chain (producer nodes with their inferred shapes/dtypes) so the user reads
"conv1's data input is rank 2 because flatten0 collapsed it" instead of a
``jax.eval_shape`` stack.

Codes are grouped by pass family:
  * ``GL0xx`` — shape/dtype propagation lint (``shape_lint.py``)
  * ``GL1xx`` — engine race analysis (``engine_race.py``)
  * ``GL2xx`` — pjit retrace guard (``retrace_guard.py``)
  * ``GL3xx`` — fusion eligibility explainer (``fusion_explain.py``)
  * ``GL4xx`` — sharding-plan lint (``shard_lint.py``)
  * ``GL5xx`` — static memory-liveness / peak-HBM planner (``memory_plan.py``)
  * ``GL6xx`` — graph-rewrite provenance verifier (``rewrite.py``)
  * ``GL7xx`` — dispatch-discipline analyzer (``dispatch_lint.py``)
  * ``GL8xx`` — concurrency analyzer (``concurrency_lint.py``)
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence

__all__ = ["Severity", "Diagnostic", "Report", "CODES", "describe_code"]


class Severity:
    """Ordered severity levels. ``ERROR`` means a bind/run would fail or
    produce wrong results; ``WARNING`` means probably-unintended behavior;
    ``INFO`` is explanatory (fusion rejections, retrace economics)."""

    INFO = "info"
    WARNING = "warning"
    ERROR = "error"

    _ORDER = {INFO: 0, WARNING: 1, ERROR: 2}

    @classmethod
    def rank(cls, sev: str) -> int:
        return cls._ORDER[sev]


# code -> (default severity, one-line description). docs/static_analysis.md
# documents each in depth; tests/test_graphlint.py triggers each one.
CODES = {
    # --- shape/dtype propagation lint ------------------------------------
    "GL001": (Severity.ERROR,
              "unbindable node: op-level shape/dtype inference failed"),
    "GL002": (Severity.ERROR,
              "underdetermined argument shape after applying all hints"),
    "GL003": (Severity.ERROR,
              "declared shape conflicts with the inferred shape"),
    "GL004": (Severity.WARNING,
              "silent dtype promotion across mixed-dtype inputs"),
    "GL005": (Severity.ERROR,
              "duplicate node name (bind-by-name would collide)"),
    "GL006": (Severity.ERROR,
              "input rank violates the op's declared rank constraints"),
    # --- engine race analysis --------------------------------------------
    "GL101": (Severity.WARNING,
              "variable appears in both const_vars and mutable_vars of one push"),
    "GL102": (Severity.WARNING,
              "wait_for_var on a variable no push ever writes"),
    "GL103": (Severity.WARNING,
              "duplicate variable inside one push's mutable_vars (write-write)"),
    "GL104": (Severity.WARNING,
              "read of a variable with no preceding write (unordered read-write)"),
    "GL105": (Severity.ERROR,
              "runtime engine-discipline violation (ops overlapped on a var)"),
    # --- retrace guard -----------------------------------------------------
    "GL201": (Severity.INFO,
              "python scalar baked into the trace as an op attribute"),
    "GL202": (Severity.WARNING,
              "weak-dtype input alongside explicitly-typed variables"),
    "GL203": (Severity.INFO,
              "shape-polymorphic inputs: compile-cache cardinality grows per shape"),
    # --- fusion explainer --------------------------------------------------
    "GL301": (Severity.INFO,
              "convolution rejected by the conv+BN fusion planner"),
    "GL302": (Severity.INFO,
              "BatchNorm not folded into its consumers by the fusion planner"),
    "GL303": (Severity.INFO,
              "generic fusion-pattern site inventory / near-miss rejection"),
    # --- sharding-plan lint ------------------------------------------------
    "GL401": (Severity.WARNING,
              "parameter silently replicated: no dim divides the model axis"),
    "GL402": (Severity.WARNING,
              "implicit reshard edge: producer/consumer layouts disagree"),
    "GL403": (Severity.WARNING,
              "batch-axis loss: op collapses the data-sharded dim mid-graph"),
    "GL404": (Severity.WARNING,
              "uneven per-device shards: a sharded dim needs padding"),
    "GL405": (Severity.INFO,
              "large replicated parameter a sharding rule could shard"),
    # --- memory planner ----------------------------------------------------
    "GL501": (Severity.WARNING,
              "predicted peak HBM per device exceeds the configured budget"),
    "GL502": (Severity.WARNING,
              "a single activation dominates the predicted memory peak"),
    # --- graph-rewrite verifier (rewrite.py) -------------------------------
    "GL601": (Severity.ERROR,
              "rewrite changed an output's inferred shape/dtype (or the "
              "argument interface)"),
    "GL602": (Severity.ERROR,
              "provenance gap: a rewritten node with no originating rule"),
    "GL603": (Severity.WARNING,
              "rewrite pipeline did not reach a fixpoint within its round "
              "budget"),
    "GL604": (Severity.ERROR,
              "rewrite-eliminated argument still referenced by a grad_req"),
    "GL605": (Severity.INFO,
              "rewrite summary: nodes folded/merged/removed with bytes-saved "
              "estimates"),
    # --- dispatch-discipline analyzer (dispatch_lint.py) -------------------
    "GL701": (Severity.WARNING,
              "host sync inside a dispatch loop: a device->host pull feeds "
              "the next iteration's dispatch"),
    "GL702": (Severity.INFO,
              "scan-able per-iteration dispatch: N identical executable "
              "calls with loop-carried state could be one lax.scan megastep"),
    "GL703": (Severity.WARNING,
              "host-side reduction of a device output where an on-device "
              "lowering exists (argmax/top-k/sampling)"),
    "GL704": (Severity.WARNING,
              "premature blocking pull serializes an in-flight async "
              "dispatch chain"),
    "GL705": (Severity.WARNING,
              "measured dispatch gap: host time between executable return "
              "and next enqueue exceeds the threshold fraction of device "
              "time"),
    # --- concurrency analyzer (concurrency_lint.py) ------------------------
    "GL801": (Severity.ERROR,
              "collective-order divergence: a collective call is "
              "control-dependent on rank-varying data (cross-rank deadlock)"),
    "GL802": (Severity.WARNING,
              "unguarded shared state: attribute mutated from >=2 thread "
              "contexts with no common lock on every mutating path"),
    "GL803": (Severity.ERROR,
              "lock-order inversion: cycle in the static lock-acquisition "
              "graph"),
    "GL804": (Severity.WARNING,
              "blocking call (collective/RPC/timeout-less wait) reached "
              "while holding a lock"),
    "GL805": (Severity.WARNING,
              "witnessed concurrency hazard: real-run lock-order inversion "
              "or >threshold hold across a dispatch seam"),
}


def describe_code(code: str) -> str:
    sev, desc = CODES[code]
    return "%s [%s] %s" % (code, sev, desc)


class Diagnostic:
    """One finding: ``code``, ``severity``, ``node``, ``message``,
    ``fix_hint``, ``provenance`` (producer chain lines)."""

    __slots__ = ("code", "severity", "node", "op", "message", "fix_hint",
                 "provenance", "pass_name")

    def __init__(self, code: str, message: str, node: Optional[str] = None,
                 op: Optional[str] = None, fix_hint: Optional[str] = None,
                 provenance: Optional[Sequence[str]] = None,
                 severity: Optional[str] = None, pass_name: str = ""):
        if code not in CODES:
            raise KeyError("unknown diagnostic code %r" % code)
        self.code = code
        self.severity = severity or CODES[code][0]
        self.node = node
        self.op = op
        self.message = message
        self.fix_hint = fix_hint
        self.provenance = list(provenance or [])
        self.pass_name = pass_name

    def format(self, color: bool = False) -> str:
        where = ""
        if self.node:
            where = " @ %s" % self.node
            if self.op:
                where += " (%s)" % self.op
        head = "%s %s%s: %s" % (self.code, self.severity, where, self.message)
        lines = [head]
        for p in self.provenance:
            lines.append("    | " + p)
        if self.fix_hint:
            lines.append("    hint: " + self.fix_hint)
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity,
            "node": self.node,
            "op": self.op,
            "message": self.message,
            "fix_hint": self.fix_hint,
            "provenance": list(self.provenance),
            "pass": self.pass_name,
        }

    def __repr__(self):
        return "<Diagnostic %s %s @ %s>" % (self.code, self.severity, self.node)


class Report:
    """An ordered collection of diagnostics from one lint run.

    ``memory_plan`` carries the GL5xx planner's non-diagnostic output (the
    per-device byte table and peak ownership, ``memory_plan.MemoryPlan
    .to_dict()``) when that pass ran with enough shape information — a clean
    graph still has a peak worth printing."""

    def __init__(self, target: str = ""):
        self.target = target
        self.diagnostics: List[Diagnostic] = []
        self.memory_plan: Optional[dict] = None
        # UNCAPPED GL402 reshard total (bytes moved per device per forward)
        # — the per-edge diagnostic list is capped at 8 for humans, but a
        # machine consumer (parallel.autoplan, JSON) must never see a
        # truncated total. None when the shard_lint pass did not run.
        self.reshard_total_bytes: Optional[int] = None
        # the GL6xx rewrite verifier's machine summary (nodes before/after,
        # per-action counts, bytes-saved estimate) — set by
        # rewrite.verify_rewrite; the GL605 diagnostic is its human line
        self.rewrite_summary: Optional[dict] = None

    def add(self, diag: Diagnostic):
        self.diagnostics.append(diag)

    def extend(self, diags):
        self.diagnostics.extend(diags)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def codes(self):
        return sorted({d.code for d in self.diagnostics})

    def at_least(self, severity: str) -> List[Diagnostic]:
        floor = Severity.rank(severity)
        return [d for d in self.diagnostics if Severity.rank(d.severity) >= floor]

    @property
    def errors(self) -> List[Diagnostic]:
        return self.at_least(Severity.ERROR)

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARNING]

    def ok(self, strict: bool = False) -> bool:
        """No errors (and, with ``strict``, no warnings either)."""
        return not self.at_least(Severity.WARNING if strict else Severity.ERROR)

    def format(self, min_severity: str = Severity.INFO) -> str:
        shown = self.at_least(min_severity)
        lines = []
        if self.target:
            lines.append("== graphlint: %s ==" % self.target)
        if not shown:
            lines.append("clean (%d suppressed below %r)"
                         % (len(self.diagnostics) - len(shown), min_severity)
                         if self.diagnostics else "clean")
        for d in shown:
            lines.append(d.format())
        n_err, n_warn = len(self.errors), len(self.warnings)
        lines.append("%d error(s), %d warning(s), %d total finding(s)"
                     % (n_err, n_warn, len(self.diagnostics)))
        return "\n".join(lines)

    def to_json(self) -> str:
        payload = {
            "target": self.target,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }
        if self.memory_plan is not None:
            payload["memory_plan"] = self.memory_plan
        if self.reshard_total_bytes is not None:
            payload["reshard_total_bytes"] = self.reshard_total_bytes
        if self.rewrite_summary is not None:
            payload["rewrite_summary"] = self.rewrite_summary
        return json.dumps(payload, indent=2)
