"""Pass manager: walks a Symbol DAG once, shares the walk across passes.

The reference's nnvm pass pipeline (``InferShape`` → ``InferType`` →
``PlanMemory`` → ``PlaceDevice``) keyed every pass off one immutable graph
with per-entry attribute columns. ``GraphContext`` is the analogue: one topo
order, one consumer map, one shape/dtype propagation table, shared by every
registered pass so adding a new check never re-derives graph structure.

Passes register with ``@graph_pass(name)`` and receive the context; they
return (or yield) ``Diagnostic`` objects. ``run_graph_passes`` assembles the
``Report``. Engine-schedule analysis lives outside this manager (it consumes
a recorded push trace, not a Symbol) — see ``engine_race.py``.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from .diagnostics import Diagnostic, Report

__all__ = ["GraphContext", "graph_pass", "run_graph_passes", "list_passes"]

_PASSES: List[Tuple[str, Callable]] = []
_warned_budgets: set = set()


def graph_pass(name: str):
    """Register a graph-lint pass. Order of registration is run order."""

    def _reg(fn):
        _PASSES.append((name, fn))
        return fn

    return _reg


def list_passes() -> List[str]:
    return [n for n, _ in _PASSES]


class GraphContext:
    """Shared per-lint state handed to every pass.

    Attributes:
      symbol        — the Symbol under analysis
      topo          — topo-ordered ``_Node`` list
      consumers     — id(node) -> [(consumer_node, out_index_consumed)]
      arg_nodes / aux_nodes — classified variable nodes
      shape_hints / type_hints — caller-provided name -> shape/dtype
      strict_shapes — True when the caller claims the hints fully bind the
                      graph (bind-time lint); underdetermined args are then
                      errors (GL002) rather than expected polymorphism (GL203)
      entry_shape / entry_dtype — (id(node), out_idx) -> shape/dtype, filled
                      by the shape_lint pass and reused by later passes
      var_shape / var_dtype — variable name -> inferred shape/dtype
      blocked       — id(node) -> reason string for nodes whose inference
                      could not run (unknown inputs / upstream failure)

    Distributed-plan state (sharding lint + memory planner):
      mesh          — parallel.mesh.MeshSpec (or a real jax Mesh) or None;
                      None skips the GL4xx pass and plans memory replicated
      rules         — parallel.sharding.ShardingRules over that mesh (built
                      via ShardingRules.infer_axes when not given)
      budget_bytes  — peak-HBM budget (from MXNET_MEMLINT_BUDGET_GB or the
                      caller); None disables GL501
      bwd_policy    — 'stash' (save every activation for backward, the
                      no-remat executor default) or 'recompute' (only
                      MXU-op outputs survive the fwd→bwd transition — the
                      remat='dots' accounting)
      train         — account grads + optimizer state + backward liveness
      entry_spec    — (id(node), out_idx) -> per-dim axis-name tuples,
                      filled by shard_lint, read by memory_plan
      memory_plan   — the planner's dict output (copied onto the Report)
    """

    def __init__(self, symbol, shape_hints=None, type_hints=None,
                 strict_shapes: Optional[bool] = None, mesh=None, rules=None,
                 budget_bytes=None, bwd_policy="stash", train=True):
        import os

        self.symbol = symbol
        self.topo = symbol._topo()
        self.shape_hints = dict(shape_hints or {})
        self.type_hints = dict(type_hints or {})
        self.strict_shapes = (bool(self.shape_hints)
                              if strict_shapes is None else strict_shapes)
        args, auxs = symbol._classified_variables()
        self.arg_nodes = args
        self.aux_nodes = auxs
        self.consumers: Dict[int, list] = {}
        for node in self.topo:
            for inp, oi in node.inputs:
                self.consumers.setdefault(id(inp), []).append((node, oi))
        # filled by shape_lint, read by retrace_guard / fusion_explain
        self.entry_shape: Dict[Tuple[int, int], Optional[tuple]] = {}
        self.entry_dtype: Dict[Tuple[int, int], object] = {}
        self.var_shape: Dict[str, Optional[tuple]] = {}
        self.var_dtype: Dict[str, object] = {}
        self.blocked: Dict[int, str] = {}
        self.blocked_vars: Dict[int, set] = {}
        # distributed-plan state (shard_lint / memory_plan)
        if mesh is None and rules is not None:
            # rules carry their mesh — passing only rules must not silently
            # skip the GL4xx pass and plan memory replicated
            mesh = getattr(rules, "mesh", None)
        self.mesh = mesh
        if rules is None and mesh is not None:
            from ..parallel.sharding import ShardingRules

            rules = ShardingRules.infer_axes(mesh)
        self.rules = rules
        if budget_bytes is None:
            raw = os.environ.get("MXNET_MEMLINT_BUDGET_GB", "").strip()
            if raw:
                try:
                    # binary GiB: the same unit every report line prints
                    budget_bytes = float(raw) * 2 ** 30
                except ValueError:
                    if raw not in _warned_budgets:
                        _warned_budgets.add(raw)
                        logging.getLogger("mxnet_tpu.graphlint").warning(
                            "MXNET_MEMLINT_BUDGET_GB=%r is not a number; "
                            "no memory budget is enforced", raw)
        self.budget_bytes = budget_bytes
        if bwd_policy not in ("stash", "recompute"):
            raise ValueError("bwd_policy must be 'stash' or 'recompute', "
                             "got %r" % (bwd_policy,))
        self.bwd_policy = bwd_policy
        self.train = bool(train)
        self.entry_spec: Dict[Tuple[int, int], tuple] = {}
        self.memory_plan = None
        # filled by shard_lint when a mesh is set: the UNCAPPED GL402 totals
        # (the diagnostic list stays capped for humans; planners/JSON
        # consumers read these)
        self.reshard_total_bytes: Optional[int] = None
        self.reshard_edges: List[dict] = []

    # ---------------------------------------------------------------- helpers
    def node_label(self, node) -> str:
        return node.name if node.is_variable else "%s(%s)" % (node.name, node.op)

    def entry_desc(self, node, out_idx: int = 0) -> str:
        """Human line for one graph entry: name(op): shape dtype."""
        sh = self.entry_shape.get((id(node), out_idx))
        dt = self.entry_dtype.get((id(node), out_idx))
        return "%s: shape=%s dtype=%s" % (
            self.node_label(node),
            "?" if sh is None else tuple(sh),
            "?" if dt is None else getattr(dt, "name", dt),
        )

    def provenance(self, node, depth: int = 4, max_lines: int = 12) -> List[str]:
        """Producer chain for ``node``: its inputs, their inputs, ... with
        inferred shapes/dtypes — the graph-level story a JAX traceback loses."""
        lines: List[str] = []
        seen = set()
        frontier = [(inp, oi, 1) for inp, oi in node.inputs]
        while frontier and len(lines) < max_lines:
            inp, oi, lvl = frontier.pop(0)
            key = (id(inp), oi)
            if key in seen:
                continue
            seen.add(key)
            lines.append("%s%s" % ("  " * (lvl - 1), self.entry_desc(inp, oi)))
            if lvl < depth:
                frontier.extend((i2, o2, lvl + 1) for i2, o2 in inp.inputs)
        return lines


def run_graph_passes(symbol, shape_hints=None, type_hints=None,
                     strict_shapes=None, passes=None, target="", mesh=None,
                     rules=None, budget_bytes=None, bwd_policy="stash",
                     train=True) -> Report:
    """Run every registered graph pass (or the named subset) over ``symbol``.

    A pass that itself crashes is reported as a GL001 on the pass, never
    swallowed and never fatal to the other passes — the linter must not be
    flakier than the thing it lints.
    """
    # passes live in sibling modules registered at import time
    from . import (shape_lint, retrace_guard, fusion_explain,  # noqa: F401
                   shard_lint, memory_plan, dispatch_lint,  # noqa: F401
                   concurrency_lint)  # noqa: F401

    ctx = GraphContext(symbol, shape_hints=shape_hints, type_hints=type_hints,
                       strict_shapes=strict_shapes, mesh=mesh, rules=rules,
                       budget_bytes=budget_bytes, bwd_policy=bwd_policy,
                       train=train)
    report = Report(target=target)
    selected = set(passes) if passes is not None else None
    if selected is not None:
        unknown = selected - {n for n, _ in _PASSES}
        if unknown:
            # a typo'd pass subset must not lint nothing and report "clean"
            raise ValueError(
                "unknown analysis pass(es) %s; registered: %s"
                % (sorted(unknown), list_passes()))
    for name, fn in _PASSES:
        if selected is not None and name not in selected:
            continue
        try:
            result = fn(ctx)
            if result:
                for d in result:
                    d.pass_name = d.pass_name or name
                    report.add(d)
        except Exception as exc:  # pragma: no cover - pass bug guard
            report.add(Diagnostic(
                "GL001",
                "analysis pass %r crashed: %s: %s"
                % (name, type(exc).__name__, exc),
                pass_name=name,
                fix_hint="report this as a graphlint bug; other passes ran",
            ))
    report.memory_plan = ctx.memory_plan
    report.reshard_total_bytes = ctx.reshard_total_bytes
    return report
