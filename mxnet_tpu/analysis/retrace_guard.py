"""Retrace guard (GL201–GL203): pjit compile-cache-busting patterns.

The executor compiles one XLA program per (program, is_train, input
shapes/dtypes) — ``jax.jit`` retraces whenever an abstract value changes
(PyGraph's capture/recompile hazard, PAPERS.md). Nothing warns when a
training script quietly forces one compile per step; these checks surface
the three classic causes *before* device time burns:

  GL201  python scalars baked into the graph as op attributes
         (``x * lr`` builds ``_mul_scalar(scalar=lr)`` — a NEW graph, hence
         a new XLA program, per distinct value)
  GL202  weak-dtype inputs next to explicitly-typed variables (the untyped
         ones default to float32 at trace time; feeding them bf16/f16 later
         is a silent retrace + upcast)
  GL203  shape-polymorphic data inputs with the expected compile-cache
         cardinality (each distinct shape tuple of each listed input is a
         separate compile, ×2 for is_train — the executor-per-bucket
         economics of BucketingModule, stated up front)
"""
from __future__ import annotations

import numpy as np

from .diagnostics import Diagnostic
from .manager import GraphContext, graph_pass
from ..ops.infer_meta import get_meta

__all__ = ["retrace_guard"]

_LIST_CAP = 6  # nodes/vars named per diagnostic before "and N more"


def _cap(names):
    names = list(names)
    if len(names) <= _LIST_CAP:
        return ", ".join(names)
    return "%s, and %d more" % (", ".join(names[:_LIST_CAP]),
                                len(names) - _LIST_CAP)


def _data_like_vars(ctx: GraphContext):
    """Arg variables that are NOT parameters: a variable is parameter-like
    when every slot it feeds is a declared param slot (infer_meta) — those
    get their shapes from backward rules; the rest (data, labels, masks)
    come from the user per batch and drive retraces."""
    param_only = {}
    for node in ctx.topo:
        if node.is_variable:
            continue
        try:
            parsed = node.parsed_attrs()
            slots = node.opdef().input_names(parsed) + node.opdef().aux_names(parsed)
        except Exception:
            slots = []
        meta = get_meta(node.op)
        for slot, (inp, _) in zip(slots, node.inputs):
            if not inp.is_variable:
                continue
            is_param = slot in meta.param_slots
            prev = param_only.get(inp.name)
            param_only[inp.name] = is_param if prev is None else (prev and is_param)
    return [n for n in ctx.arg_nodes
            if not param_only.get(n.name, False)]


@graph_pass("retrace_guard")
def retrace_guard(ctx: GraphContext):
    diags = []

    # ---- GL201: scalar attrs baked into the trace -----------------------
    scalar_nodes = []
    for node in ctx.topo:
        if node.is_variable:
            continue
        try:
            parsed = node.parsed_attrs()
        except Exception:
            continue
        if "scalar" in parsed and parsed["scalar"] is not None:
            scalar_nodes.append(node)
    if scalar_nodes:
        values = sorted({float(n.parsed_attrs()["scalar"]) for n in scalar_nodes})
        diags.append(Diagnostic(
            "GL201",
            "%d node(s) bake a python scalar into the graph (%s); every "
            "distinct value is a distinct graph and hence a distinct XLA "
            "compile — a per-step-varying scalar (lr, loss scale) forces one "
            "compile per step"
            % (len(scalar_nodes),
               _cap("%s=%g" % (n.name, float(n.parsed_attrs()["scalar"]))
                    for n in scalar_nodes)),
            node=scalar_nodes[0].name, op=scalar_nodes[0].op,
            fix_hint="if the value varies at runtime, feed it as a Variable "
                     "input instead of an attribute; %d distinct value(s) "
                     "seen in this graph" % len(values),
        ))

    # ---- GL202: weak-dtype inputs beside explicitly-typed ones ----------
    declared = {}
    for node in ctx.arg_nodes:
        if "__dtype__" in node.attrs:
            declared[node.name] = np.dtype(node.attrs["__dtype__"])
        elif node.name in ctx.type_hints:
            declared[node.name] = np.dtype(ctx.type_hints[node.name])
    non_f32 = {n: d for n, d in declared.items() if d != np.dtype(np.float32)}
    if non_f32:
        weak = [n.name for n in _data_like_vars(ctx) if n.name not in declared]
        if weak:
            diags.append(Diagnostic(
                "GL202",
                "inputs %s carry no dtype while %s are explicitly %s; the "
                "untyped ones weak-default to float32 at trace time, so "
                "feeding them anything else later silently retraces (and "
                "mixed math upcasts)"
                % (_cap(weak), _cap(sorted(non_f32)),
                   sorted({d.name for d in non_f32.values()})),
                node=weak[0],
                fix_hint="declare Variable(dtype=...) (or pass type_dict at "
                         "bind) for every data input of a reduced-precision "
                         "graph",
            ))

    # ---- GL203: shape-polymorphic inputs → compile-cache cardinality ----
    poly = [n.name for n in _data_like_vars(ctx)
            if ctx.var_shape.get(n.name) is None]
    if poly and not ctx.strict_shapes:
        diags.append(Diagnostic(
            "GL203",
            "inputs %s are shape-polymorphic: expected compile-cache "
            "cardinality is (distinct shape tuples of %s) x 2 for "
            "is_train - each combination traces and compiles a fresh XLA "
            "executable, and bound buffers are not donated across shapes"
            % (_cap(poly), _cap(poly)),
            node=poly[0],
            fix_hint="pad/bucket batches to a fixed set of shapes "
                     "(BucketingModule economics) and keep that set small",
        ))
    return diags
