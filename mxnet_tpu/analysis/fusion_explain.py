"""Fusion-eligibility explainer (GL301/GL302/GL303).

``fusion.plan`` silently skips every subgraph it cannot rewrite — correct,
but invisible: a model author who expected the fused path has no way to
learn *which* predicate failed short of reading the planner. This pass
re-runs the plan and reports, for every rejected Convolution (GL301) and
every unfolded BatchNorm (GL302), the exact predicate, quoting
``fusion.conv_reject_reason`` / ``fusion.bn_reject_reason`` for op-level
predicates and re-deriving the consumer-structure predicates for fold
rejections.

GL303 covers the generic pattern engine (ops/fusion_patterns.py): for
every node a pattern ALMOST rooted (a FullyConnected whose consumer is not
a fusable Activation, a broadcast_add whose LayerNorm chain broke one link
deep, ...) it quotes the pattern's ``reject_reason``; for every planned
pattern root it reports the site inventory — the engage itself is a
per-shape trace-time decision (the fusion_tune measured verdict, whose
tuned-and-rejected reasons carry the measured fused-vs-baseline µs).

All findings are INFO severity: an unfused graph is slower, not wrong.
"""
from __future__ import annotations

from .diagnostics import Diagnostic
from .manager import GraphContext, graph_pass

__all__ = ["fusion_explain"]


def _is_relu(node):
    return (node.op == "Activation"
            and node.parsed_attrs().get("act_type") == "relu")


def _explain_no_fold(ctx: GraphContext, node, directives):
    """Why an eligible BatchNorm's directive has fold=False — mirrors the
    consumer walk in fusion.plan, returning the failed predicate."""
    from .. import fusion

    output_ids = {id(n) for n, _ in ctx.symbol._outputs}
    if id(node) in output_ids:
        return ("its output is a program output and must materialize; the "
                "fold would save nothing")
    cons = ctx.consumers.get(id(node), [])
    if not cons:
        return "its output is a graph head; there is no consumer to fold into"
    bad_index = [c for c, oi in cons if oi != 0]
    if bad_index:
        return ("outputs other than the normalized activation are consumed "
                "(e.g. by %s)" % bad_index[0].name)
    targets = [c for c, _ in cons]
    src, src_desc = node, "the BN output"
    if len(targets) == 1 and _is_relu(targets[0]):
        relu = targets[0]
        relu_cons = ctx.consumers.get(id(relu), [])
        if any(oi != 0 for _, oi in relu_cons):
            return "the relu's secondary outputs are consumed"
        targets = [c for c, _ in relu_cons]
        src, src_desc = relu, "the relu(BN) output"
        if id(relu) in output_ids:
            return ("the relu output is a program output and must "
                    "materialize; the fold would save nothing")
        if not targets:
            return "the relu output is a graph head; nothing to fold into"
    for c in targets:
        d = directives.get(id(c))
        if d is None or d.get("kind") != "conv":
            reason = fusion.conv_reject_reason(c)
            return ("%s feeds %s(%s), which is not a fusable convolution: %s"
                    % (src_desc, c.name, c.op, reason))
        if not (c.inputs and c.inputs[0][0] is src):
            return ("%s feeds %s's weight input, not its data input"
                    % (src_desc, c.name))
    return "planner declined the fold (unmatched consumer pattern)"


@graph_pass("fusion_explain")
def fusion_explain(ctx: GraphContext):
    from .. import fusion

    diags = []
    # same output_ids the executor passes: the explained plan must be the
    # plan that actually runs (graph-output nodes are never folded/deferred)
    directives = fusion.plan(
        ctx.topo, output_ids={id(n) for n, _ in ctx.symbol._outputs})
    for node in ctx.topo:
        if node.is_variable:
            continue
        if node.op == "Convolution":
            reason = fusion.conv_reject_reason(node)
            if reason is not None:
                diags.append(Diagnostic(
                    "GL301",
                    "not eligible for the Pallas conv+BN path: %s" % reason,
                    node=node.name, op=node.op,
                    fix_hint="this conv runs on the ordinary XLA lowering; "
                             "see docs/PERF.md §6 for the supported shapes",
                ))
        elif node.op == "BatchNorm":
            reason = fusion.bn_reject_reason(node)
            if reason is not None:
                diags.append(Diagnostic(
                    "GL302",
                    "not eligible for fusion: %s" % reason,
                    node=node.name, op=node.op,
                ))
                continue
            d = directives.get(id(node))
            if d is not None and d.get("kind") == "bn" and not d.get("fold"):
                diags.append(Diagnostic(
                    "GL302",
                    "eligible but not folded: %s" % _explain_no_fold(ctx, node, directives),
                    node=node.name, op=node.op,
                    fix_hint="a fold needs every consumer of the BN(+relu) "
                             "output to be the data input of a fusable conv",
                ))
    diags.extend(_explain_patterns(ctx, directives))
    return diags


def _explain_patterns(ctx: GraphContext, directives):
    """GL303: NEAR-MISS rejections of the generic pattern engine — a node
    that almost rooted a pattern (e.g. a FullyConnected whose fusable
    Activation consumer is not its sole consumer) with the failed
    predicate. Deliberately quiet: a node that simply isn't a pattern's
    shape is not a finding (a clean model must lint clean), and the
    planned-site inventory lives on ``Report.memory_plan["fusion"]`` and
    the serving cache's ``fusion_sites()``, not here."""
    from .. import fusion
    from ..ops.fusion_patterns import get_patterns

    diags = []
    modes = fusion.enabled_patterns()
    pctx = fusion._PlanCtx(
        ctx.consumers, {id(n) for n, _ in ctx.symbol._outputs}, directives)
    for node in ctx.topo:
        if node.is_variable or directives.get(id(node)) is not None:
            continue
        for pat in get_patterns():
            if modes.get(pat.name, "0") == "0":
                continue
            reason = pat.reject_reason(node, pctx)
            if reason is not None:
                diags.append(Diagnostic(
                    "GL303",
                    "not rooted by the %r pattern: %s" % (pat.name, reason),
                    node=node.name, op=node.op,
                    fix_hint="pattern matchers are structural; see "
                             "ops/fusion_patterns.py for the contract",
                ))
                break
    return diags
