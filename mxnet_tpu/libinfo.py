"""Locate the native libraries and report the version (reference:
python/mxnet/libinfo.py find_lib_path/__version__ — there it found
libmxnet.so; here the native artifacts are the engine/IO/image/predict
shared objects built under ``build/``)."""
from __future__ import annotations

import os

from . import __version__  # noqa: F401  (single source of truth: __init__)

__all__ = ["find_lib_path", "__version__"]

_NATIVE_LIBS = (
    "libmxtpu_engine.so",
    "libmxtpu_io.so",
    "libmxtpu_image.so",
    "libmxtpu_predict.so",
)


def find_lib_path():
    """Paths of every built native library (possibly empty: the Python
    stack runs without them — they are accelerators, not prerequisites)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = [os.path.join(root, "build"),
                  os.path.join(root, "lib"),
                  os.environ.get("MXNET_LIBRARY_PATH", "")]
    found = []
    for d in candidates:
        if not d or not os.path.isdir(d):
            continue
        for name in _NATIVE_LIBS:
            p = os.path.join(d, name)
            if os.path.exists(p) and p not in found:
                found.append(p)
    return found
