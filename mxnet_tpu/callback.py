"""Training callbacks.

Counterpart of the reference's python/mxnet/callback.py (Speedometer :89,
do_checkpoint :39, module_checkpoint :11, log_train_metric :70).
"""
from __future__ import annotations

import logging
import math
import time

__all__ = ["Speedometer", "do_checkpoint", "module_checkpoint", "log_train_metric", "ProgressBar"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False,
                      keep=None):
    """Epoch-end callback checkpointing a module (reference: callback.py:11).

    ``keep`` (default: ``MXNET_CHECKPOINT_KEEP``, unlimited when unset)
    retains only the last K epoch checkpoints so long elastic runs don't
    grow disk without bound. Deletion is manifest-aware: the newest epoch
    whose files are COMPLETE — including, for a sharded ``.states``
    pointer, the whole shard set it references — is never deleted, and a
    deleted sharded pointer takes its backing shard directory with it
    (checkpoint.prefix_retention, docs/FAULT_TOLERANCE.md)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
            _apply_keep(prefix, keep)

    return _callback


def _apply_keep(prefix, keep):
    from . import checkpoint as ckpt

    if keep is None:
        k = ckpt.checkpoint_keep()
    else:
        k = int(keep)
        if k <= 0:
            # same contract as MXNET_CHECKPOINT_KEEP: non-positive warns
            # and disables (a negative k would slice epochs[:-k] wrong)
            logging.warning("checkpoint keep=%r is not a positive int; "
                            "retention disabled", keep)
            k = None
    if k:
        ckpt.prefix_retention(prefix, k)


def do_checkpoint(prefix, period=1, keep=None):
    """Epoch-end callback saving symbol+params (reference: callback.py:39);
    ``keep`` retains the last K epochs (see ``module_checkpoint``)."""
    from .model import save_checkpoint

    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
            _apply_keep(prefix, keep)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the training metric every ``period`` batches
    (reference: callback.py:70)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f", param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """Log samples/sec every ``frequent`` batches (reference: callback.py:89) —
    the throughput number the benchmarks track — plus step time, and MFU when
    ``flops_per_sample`` is given and the device's bf16 peak is known
    (device_info.py). Training logs then carry the BASELINE scoreboard
    numbers directly.

    When telemetry is enabled the window duration comes from the registry's
    per-step rows (``Module.fit`` marks one per batch) — ONE wall-clock
    source of truth shared with ``mxtrace``/``bench.py`` instead of a
    second ``time.time()`` path that can disagree with the trace."""

    def __init__(self, batch_size, frequent=50, flops_per_sample=None):
        self.batch_size = batch_size
        self.frequent = frequent
        self.flops_per_sample = flops_per_sample
        self.init = False
        self.tic = 0
        self.last_count = 0
        self._peak = None  # resolved lazily from the default device
        self._tic_step = None  # newest telemetry step id when tic was set

    @staticmethod
    def _newest_step():
        from . import telemetry

        if not telemetry.enabled():
            return None
        rows = telemetry.step_rows(last=1)
        return rows[-1]["step"] if rows else None

    def _set_tic(self):
        self.tic = time.time()
        self._tic_step = self._newest_step()

    def _window(self):
        """``(seconds, batches)`` since the last report. Telemetry step rows
        are used only when they are FRESH — marked after this window's tic
        (a loop that never calls ``mark_step``, e.g. eval/score after a fit,
        must not recycle the fit's stale rows as its own speed) — else the
        local wall clock."""
        from . import telemetry

        if telemetry.enabled() and self._tic_step is not None:
            rows = telemetry.step_rows(last=self.frequent + 1)
            fresh = [r for r in rows if r["step"] > self._tic_step
                     and r["wall_ms"] is not None]
            newest = rows[-1]["step"] if rows else self._tic_step
            delta = newest - self._tic_step
            # contiguity: every step of the window is present and timed
            if fresh and len(fresh) == delta and delta <= self.frequent:
                return (max(sum(r["wall_ms"] for r in fresh) / 1000.0, 1e-9),
                        delta)
        return max(time.time() - self.tic, 1e-9), self.frequent

    def _mfu(self, speed):
        if not self.flops_per_sample:
            return None
        if self._peak is None:
            try:
                import jax

                from .device_info import bf16_peak_flops

                self._peak = bf16_peak_flops(jax.devices()[0].device_kind) or 0
            except Exception:
                self._peak = 0
        return speed * self.flops_per_sample / self._peak if self._peak else None

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count

        if self.init:
            if count % self.frequent == 0:
                dt, nbatches = self._window()
                speed = nbatches * self.batch_size / dt
                step_ms = 1000.0 * dt / nbatches
                mfu = self._mfu(speed)
                perf = "Speed: %.2f samples/sec\tStep: %.1f ms" % (speed, step_ms)
                if mfu is not None:
                    perf += "\tMFU: %.1f%%" % (100 * mfu)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    param.eval_metric.reset()
                    for name, value in name_value:
                        logging.info("Epoch[%d] Batch [%d]\t%s\tTrain-%s=%f",
                                     param.epoch, count, perf, name, value)
                else:
                    logging.info("Iter[%d] Batch [%d]\t%s",
                                 param.epoch, count, perf)
                self._set_tic()
        else:
            self.init = True
            self._set_tic()


class ProgressBar:
    """Text progress bar per epoch (reference: callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    """Epoch-end eval callback: log every validation metric value
    (reference: callback.py LogValidationMetricsCallback). Useful as
    ``eval_end_callback`` when a Speedometer with ``auto_reset`` has
    cleared the training metric mid-epoch."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
