"""Device capability table (bf16 peak FLOP/s) for MFU accounting.

The reference never needed this — CUDA exposes clock×cores — but TPU peak
comes from public spec sheets keyed on ``device_kind``. Used by bench.py and
callback.Speedometer's MFU display.
"""
__all__ = ["bf16_peak_flops"]

# public spec-sheet numbers
_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU v7": 2307e12,
}


def bf16_peak_flops(device_kind):
    """bf16 peak for a device kind, tolerant of naming variants ("TPU v5p
    slice" → "TPU v5p"); None when unknown — callers must not guess."""
    if device_kind in _PEAK:
        return _PEAK[device_kind]
    best = None
    for kind, peak in _PEAK.items():
        if device_kind.startswith(kind):
            if best is None or len(kind) > len(best[0]):
                best = (kind, peak)
    return best[1] if best else None
