"""Optimizers.

Counterpart of the reference's python/mxnet/optimizer.py (Optimizer registry
:10-, SGD :279, Adam :451, get_updater). Each ``update(index, weight, grad,
state)`` lowers to ONE fused update op from ``ops/optimizer_ops.py`` where the
reference has a device kernel (sgd/sgd_mom/adam/rmsprop), so XLA fuses
rescale+clip+wd+update into a single HBM pass per weight — the reference's
device-side kvstore-updater path, TPU-native.

The jittable FLAT kernels (``flat_kernel``) are the shared lowering behind
two consumers: the kvstore bucket engine's fused sharded weight update
(``kvstore_bucket``) and the row-sparse LAZY update
(``update_row_sparse``, docs/SPARSE.md) — one expression tree, so sharded,
replicated and lazy-sparse all land within reassociation drift of each
other.

**Lazy-update contract** (``update_row_sparse``): a row-sparse gradient
updates ONLY the rows its index set names — weight rows outside the set are
untouched, and their optimizer state stays *bit-identical to seed* (for
Adam that means mean/var are still exactly zero, never decayed by a
phantom zero-gradient step). The per-key update count still ticks once per
round, so lr schedules match the dense path. Enforced by construction
(``sparse.RowSparseState`` stores no row it never updated) and regression-
tested in tests/test_sparse.py — including against a dense-wire fallback
round, which must convert back to a row set before updating.
"""
from __future__ import annotations

import functools
import math

import numpy as np

from . import ndarray as nd
from .ndarray import imperative_invoke, zeros

__all__ = [
    "Optimizer",
    "SGD",
    "NAG",
    "SGLD",
    "DCASGD",
    "Adam",
    "AdaGrad",
    "RMSProp",
    "AdaDelta",
    "Test",
    "create",
    "register",
    "get_updater",
    "Updater",
    "flat_kernel",
    "FLAT_KERNELS",
]


# ------------------------------------------------------------------ flat
# jittable flat optimizer kernels — each mirrors the corresponding fused op
# in ops/optimizer_ops.py exactly (same expression tree). ``lr``/``wd``
# arrive at runtime as scalars or per-element vectors; everything in
# ``hyper`` is a trace-time constant. Shared by kvstore_bucket's sharded
# update and the row-sparse lazy update below.

def _flat_sgd(hyper):
    import jax.numpy as jnp

    rg, clip = hyper["rescale_grad"], hyper["clip_gradient"]
    mu = hyper["momentum"]

    def fn(w, g, states, lr, wd):
        g = g * rg
        if clip and clip > 0:
            g = jnp.clip(g, -clip, clip)
        if mu:
            (mom,) = states
            new_mom = mu * mom - lr * (g + wd * w)
            return w + new_mom, (new_mom,)
        return w - lr * (g + wd * w), ()

    return fn


def _flat_adam(hyper):
    import jax.numpy as jnp

    rg, clip = hyper["rescale_grad"], hyper["clip_gradient"]
    b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["epsilon"]

    def fn(w, g, states, lr, wd):
        g = g * rg
        if clip and clip > 0:
            g = jnp.clip(g, -clip, clip)
        g = g + wd * w
        mean, var = states
        new_mean = b1 * mean + (1 - b1) * g
        new_var = b2 * var + (1 - b2) * jnp.square(g)
        w = w - lr * new_mean / (jnp.sqrt(new_var) + eps)
        return w, (new_mean, new_var)

    return fn


FLAT_KERNELS = {"sgd": _flat_sgd, "adam": _flat_adam}


@functools.lru_cache(maxsize=64)
def _jitted_flat_kernel(kind, hyper_key, n_states):
    """One compiled row-update executable per (kind, hyper) — shapes/dtypes
    specialize through jit's own cache."""
    import jax

    kernel = FLAT_KERNELS[kind](dict(hyper_key))

    def run(w, g, states, lr, wd):
        w_new, s_new = kernel(w, g, tuple(states), lr, wd)
        return (w_new,) + tuple(s_new)

    return jax.jit(run)


def flat_kernel(kind, hyper):
    """The raw (unjitted) flat kernel for a ``flat_update_spec`` family."""
    return FLAT_KERNELS[kind](hyper)


class Optimizer:
    """Base optimizer with the reference's registry / lr&wd-mult machinery."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() not in Optimizer.opt_registry:
            raise ValueError("Cannot find optimizer %s" % name)
        return Optimizer.opt_registry[name.lower()](**kwargs)

    def __init__(
        self,
        rescale_grad=1.0,
        param_idx2name=None,
        wd=0.0,
        clip_gradient=None,
        learning_rate=0.01,
        lr_scheduler=None,
        sym=None,
        begin_num_update=0,
    ):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise TypeError("param_idx2name should be a dict of param indexes to names.")
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self.set_lr_mult({})
        self.set_wd_mult({})

    # ------------------------------------------------------------- state mgmt
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def flat_update_spec(self):
        """Spec for the kvstore bucket engine's fused sharded weight update
        (kvstore_bucket, docs/PERF.md §11): ``(kind, hyper, n_states)``
        describing a jittable flat-1D update whose math is identical to this
        optimizer's fused per-key op, or ``None`` when the optimizer has no
        flat lowering (the engine then falls back to the replicated
        update). ``hyper`` must be trace-time constants; per-key lr/wd
        arrive at runtime as vectors. The same spec powers the row-sparse
        LAZY update (``update_row_sparse``) — sparse-aware by construction:
        the kernel runs over the touched rows only."""
        return None

    def create_state_row_sparse(self, index, weight):
        """State for a row-sparse-gradient parameter: a lazily-grown
        ``sparse.RowSparseState`` with one row slot per flat-kernel state
        (docs/SPARSE.md). Optimizers without a flat lowering fall back to
        the dense state (their row-sparse updates densify, with a one-time
        warning — lazy semantics need SGD/Adam)."""
        spec = self.flat_update_spec()
        if spec is None:
            if not getattr(self, "_warned_no_lazy", False):
                self._warned_no_lazy = True
                import logging

                logging.getLogger("mxnet_tpu.sparse").warning(
                    "optimizer %s has no flat_update_spec(): row-sparse "
                    "gradients densify and the update is NOT lazy (untouched "
                    "rows see a zero-gradient step)", type(self).__name__)
            return self.create_state(index, weight)
        from .sparse import RowSparseState

        _, _, n_states = spec
        return RowSparseState(weight.shape, weight.dtype, n_states)

    def update_row_sparse(self, index, weight, grad, state):
        """Lazy row update (reference: the ``lazy_update=True`` path of
        sgd_update/adam_update over kRowSparseStorage). Applies the flat
        kernel to exactly ``grad.indices``'s rows of ``weight`` and
        ``state``; every other row — weight AND optimizer state — is
        bit-untouched. The per-key update count ticks once per call, so lr
        schedules stay identical to the dense path."""
        from .sparse import RowSparseNDArray, RowSparseState

        assert isinstance(grad, RowSparseNDArray), type(grad)
        spec = self.flat_update_spec()
        if spec is None or not isinstance(state, RowSparseState):
            # no flat lowering (or a dense state from a dense resume):
            # densify — correctness preserved, laziness forfeited
            self.update(index, weight, grad.to_dense(), state)
            return
        kind, hyper, n_states = spec
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if kind == "adam":
            # same host-side bias-correction fold Adam.update applies
            t = self._index_update_count[index]
            lr *= (math.sqrt(1.0 - hyper["beta2"] ** t)
                   / (1.0 - hyper["beta1"] ** t))
        rows = grad.indices.asnumpy().astype(np.int64)
        if not rows.size:
            return
        import jax.numpy as jnp

        fn = _jitted_flat_kernel(
            kind, tuple(sorted(hyper.items())), n_states)
        w_jax = weight._jax()
        w_rows = w_jax[rows]
        g_rows = grad.values._jax().astype(w_rows.dtype)
        s_rows = tuple(jnp.asarray(s) for s in state.gather(rows))
        out = fn(w_rows, g_rows, s_rows, np.float32(lr), np.float32(wd))
        weight._set_jax(w_jax.at[rows].set(out[0]))
        state.scatter(rows, [np.asarray(s) for s in out[1:]])

    # ----------------------------------------------------------------- mults
    def set_lr_mult(self, args_lr_mult):
        """Per-param lr multipliers; symbol ``__lr_mult__`` attrs feed in too
        (reference: optimizer.py set_lr_mult)."""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """Per-param wd multipliers; bias/gamma/beta default to wd 0 like the
        reference (no weight decay on 1-d params)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    # ------------------------------------------------------------- schedules
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common_attrs(self, lr, wd):
        attrs = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            attrs["clip_gradient"] = self.clip_gradient
        return attrs


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum (reference: optimizer.py:279), lowered to the fused
    sgd_update / sgd_mom_update ops."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        attrs = self._common_attrs(lr, wd)
        if state is not None:
            attrs["momentum"] = self.momentum
            imperative_invoke("sgd_mom_update", [weight, grad, state], attrs, out=[weight, state])
        else:
            imperative_invoke("sgd_update", [weight, grad], attrs, out=[weight])

    def flat_update_spec(self):
        """Flat lowering of sgd_update / sgd_mom_update (ops/optimizer_ops)."""
        return ("sgd", {"momentum": self.momentum,
                        "rescale_grad": self.rescale_grad,
                        "clip_gradient": self.clip_gradient or 0.0},
                1 if self.momentum != 0.0 else 0)


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference: optimizer.py:380)."""

    def flat_update_spec(self):
        return None  # Nesterov math differs from the flat sgd kernel

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom[:] = mom * self.momentum + grad + wd * weight
            grad[:] = grad + self.momentum * mom
            weight[:] = weight - lr * grad
        else:
            weight[:] = weight - lr * (grad + wd * weight)


@register
class ccSGD(SGD):
    """Deprecated alias of SGD (reference: optimizer.py:445 — there it was a
    C++-side fast path; here every optimizer already lowers into the compiled
    step, so the distinction is void)."""


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference: optimizer.py:416)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.random_normal(
            loc=0.0, scale=math.sqrt(lr), shape=weight.shape, ctx=weight.context
        )
        weight[:] = weight - lr / 2 * (grad + wd * weight) + noise


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py:325)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (
            zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
            weight.copy(),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mom, previous_weight = state
        if mom is not None:
            mom[:] = mom * self.momentum
            mom[:] = mom - lr * (
                grad + wd * weight + self.lamda * grad * grad * (weight - previous_weight)
            )
        else:
            mom = -lr * (
                grad + wd * weight + self.lamda * grad * grad * (weight - previous_weight)
            )
        previous_weight[:] = weight
        weight[:] = weight + mom


@register
class Adam(Optimizer):
    """Adam (reference: optimizer.py:451) with the reference's bias-corrected
    effective lr, lowered to the fused adam_update op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # mean
            zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # var
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        attrs = self._common_attrs(lr, wd)
        attrs.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)
        imperative_invoke("adam_update", [weight, grad, mean, var], attrs, out=[weight, mean, var])

    def flat_update_spec(self):
        """Flat lowering of adam_update; the per-key bias-corrected lr is
        folded host-side into the lr segment vector (same fold ``update``
        does), so per-key step counts stay exact."""
        return ("adam", {"beta1": self.beta1, "beta2": self.beta2,
                         "epsilon": self.epsilon,
                         "rescale_grad": self.rescale_grad,
                         "clip_gradient": self.clip_gradient or 0.0}, 2)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference: optimizer.py:499)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        history = state
        history[:] = history + grad * grad
        weight[:] = weight - lr * (grad / nd.sqrt(history + self.float_stable_eps) + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp; centered=True uses Alex Graves' variant like the reference
    (optimizer.py:536), via the fused rmsprop/rmspropalex ops."""

    def __init__(
        self,
        learning_rate=0.001,
        gamma1=0.9,
        gamma2=0.9,
        epsilon=1e-8,
        centered=False,
        clip_weights=None,
        **kwargs,
    ):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # n
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # g
                zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # delta
            )
        return (zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),)  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        attrs = self._common_attrs(lr, wd)
        attrs.update(gamma1=self.gamma1, epsilon=self.epsilon)
        if self.clip_weights is not None:
            attrs["clip_weights"] = self.clip_weights
        if not self.centered:
            (n,) = state
            imperative_invoke("rmsprop_update", [weight, grad, n], attrs, out=[weight, n])
        else:
            n, g, delta = state
            attrs["gamma2"] = self.gamma2
            imperative_invoke(
                "rmspropalex_update", [weight, grad, n, g, delta], attrs, out=[weight, n, g, delta]
            )


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: optimizer.py:605)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # accumulated g
            zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # accumulated delta
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1.0 - self.rho) * grad * grad
        current_delta = nd.sqrt(acc_delta + self.epsilon) / nd.sqrt(acc_g + self.epsilon) * grad
        acc_delta[:] = self.rho * acc_delta + (1.0 - self.rho) * current_delta * current_delta
        weight[:] = weight - current_delta - wd * weight


@register
class Test(Optimizer):
    """Trivial optimizer for tests (reference: optimizer.py:653)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight[:] = weight + grad * self.rescale_grad
        state[:] = weight


class Updater:
    """Closure applying an optimizer per key with lazily-created state
    (reference: get_updater / kvstore _updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        from .sparse import RowSparseNDArray, RowSparseState, from_dense

        if isinstance(grad, RowSparseNDArray):
            if index not in self.states:
                self.states[index] = \
                    self.optimizer.create_state_row_sparse(index, weight)
            self.optimizer.update_row_sparse(index, weight, grad,
                                             self.states[index])
            return
        if isinstance(self.states.get(index), RowSparseState):
            # a key that trained row-sparse now sees a DENSE gradient (e.g.
            # a sparse-resumed table fed by a dense producer): keep the
            # key's lazy contract — its nonzero rows ARE its touched set —
            # instead of crashing Optimizer.update on the foreign state
            self.optimizer.update_row_sparse(index, weight, from_dense(grad),
                                             self.states[index])
            return
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        import pickle

        self.states = pickle.loads(states) if isinstance(states, bytes) else states

    def get_states(self):
        import pickle

        return pickle.dumps(self.states)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
