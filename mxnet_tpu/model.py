"""Checkpointing + legacy FeedForward model API.

Counterpart of the reference's python/mxnet/model.py (save_checkpoint :319,
load_checkpoint :349, FeedForward :387). Checkpoints are the reference's
three artifacts — ``<prefix>-symbol.json`` + ``<prefix>-NNNN.params`` (+
optional ``.states``) — in the reference's binary layout, so artifacts
interoperate (SURVEY.md §5.4).
"""
from __future__ import annotations

import logging
import os

import numpy as np

from . import io as mxio
from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

BASE_ESTIMATOR = object
try:
    from sklearn.base import BaseEstimator

    BASE_ESTIMATOR = BaseEstimator
except ImportError:
    pass

__all__ = ["save_checkpoint", "load_checkpoint", "find_last_checkpoint",
           "resume_or_init", "FeedForward",
           "_create_kvstore", "_initialize_kvstore",
           "_update_params_on_kvstore", "_update_params"]

# Reference-parity aliases (python/mxnet/model.py:40-116 kept these private
# helpers ON model; downstream training loops import them from here). The
# implementations live in kvstore_helper — including the bucketed per-key
# priority schedule _update_params[_on_kvstore] run on dist stores
# (docs/PERF.md §11).
from .kvstore_helper import (                                  # noqa: E402
    create_kvstore as _create_kvstore,
    initialize_kvstore as _initialize_kvstore,
    update_params_on_kvstore as _update_params_on_kvstore,
    update_params as _update_params,
)


# per-prefix engine variables: successive epoch writes to one prefix are
# serialized; readers (load/find_last_checkpoint) wait on the same var.
# Each entry is (engine, var): vars do NOT survive set_engine_type, and a
# stale id may even alias a var the NEW engine issued, so the engine
# identity stored here is the authoritative staleness check (the swap
# already drained the old engine, so a stale entry is simply dropped).
_ckpt_vars = {}
# a failed async write must not vanish: the error re-raises at the next
# save/load/find on the same prefix (and is logged when it happens)
_ckpt_errors = {}


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """(reference: model.py:319).

    The device->host parameter fetch is synchronous (the arrays may be
    mutated by the next step), but the DISK write is pushed through the
    execution engine (mx.engine — the reference's Engine::Push with a
    write var on the prefix), so epoch checkpoints overlap with training
    under ThreadedEngine and serialize under MXNET_ENGINE_TYPE=NaiveEngine.
    ``nd.waitall()`` (or any load/find on the same prefix) drains the
    pending write."""
    from . import engine

    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    # snapshot on host NOW; the engine thread only touches the file
    snap = {k: nd.array(v.asnumpy()) if isinstance(v, nd.NDArray) else nd.array(v)
            for k, v in save_dict.items()}
    param_name = "%s-%04d.params" % (prefix, epoch)
    key = os.path.abspath(prefix)
    _raise_pending_ckpt_error(key)
    eng = engine.get()
    entry = _ckpt_vars.get(key)
    if entry is None or entry[0] is not eng:
        _ckpt_vars[key] = (eng, eng.new_variable())
    var = _ckpt_vars[key][1]

    def write():
        try:
            # temp + os.replace: a crash mid-write leaves the previous
            # epoch's file intact, never a torn one a later load chokes on
            from .checkpoint import atomic_replace

            with atomic_replace(param_name) as tmp:
                nd.save(tmp, snap)
            logging.info('Saved checkpoint to "%s"', param_name)
        except Exception as exc:  # surfaced at the next save/load/find
            logging.error('checkpoint write to "%s" FAILED: %s',
                          param_name, exc)
            _ckpt_errors[key] = exc

    eng.push(write, const_vars=(), mutable_vars=(var,))


def _raise_pending_ckpt_error(key):
    exc = _ckpt_errors.pop(key, None)
    if exc is not None:
        raise MXNetError("earlier async checkpoint write failed: %s" % exc) \
            from exc


def _wait_checkpoint_writes(prefix):
    key = os.path.abspath(prefix)
    entry = _ckpt_vars.get(key)
    if entry is not None:
        from . import engine

        eng, var = entry
        if eng is engine.get():
            eng.wait_for_var(var)
        else:
            # engine swapped since the write was pushed: set_engine_type
            # drained the old engine, so the write already landed
            del _ckpt_vars[key]
    _raise_pending_ckpt_error(key)


def find_last_checkpoint(prefix):
    """Latest saved epoch for ``prefix``, or None. Backs crash-resume
    (SURVEY.md §5.3/§5.4: failure recovery on gang-scheduled pods is
    checkpoint-resume, not elastic membership)."""
    import glob
    import re

    _wait_checkpoint_writes(prefix)
    best = None
    for path in glob.glob(glob.escape(prefix) + "-*.params"):
        m = re.search(r"-(\d{4,})\.params$", path)
        if m:
            ep = int(m.group(1))
            best = ep if best is None else max(best, ep)
    return best


def resume_or_init(prefix):
    """(begin_epoch, arg_params, aux_params) from the newest checkpoint, or
    (0, None, None) when none exists — feed straight into ``Module.fit``::

        begin, args, auxs = mx.model.resume_or_init("ckpt/resnet")
        mod.fit(..., begin_epoch=begin, arg_params=args, aux_params=auxs,
                epoch_end_callback=mx.callback.do_checkpoint("ckpt/resnet"))
    """
    last = find_last_checkpoint(prefix)
    if last is None:
        return 0, None, None
    _, arg_params, aux_params = load_checkpoint(prefix, last)
    logging.info("Resuming from %s epoch %d", prefix, last)
    return last, arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(reference: model.py:349) → (symbol, arg_params, aux_params).
    A torn/partial params file raises a structured ``MXNetError`` naming
    the path (checkpoint.load_ndarrays_checked) instead of a raw
    deserialization error far from the cause."""
    from .checkpoint import load_ndarrays_checked

    _wait_checkpoint_writes(prefix)
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = load_ndarrays_checked("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward(BASE_ESTIMATOR):
    """sklearn-style training wrapper (reference: model.py:387 FeedForward).
    Thin adapter over Module — the reference's _train_multi_device loop is the
    Module fit path here."""

    def __init__(
        self,
        symbol,
        ctx=None,
        num_epoch=None,
        epoch_size=None,
        optimizer="sgd",
        initializer=None,
        numpy_batch_size=128,
        arg_params=None,
        aux_params=None,
        allow_extra_params=False,
        begin_epoch=0,
        **kwargs,
    ):
        from .context import current_context
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx or [current_context()]
        if not isinstance(self.ctx, list):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.kwargs = kwargs.copy()
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self._pred_exec = None

    def _init_iter(self, X, y, is_train):
        if isinstance(X, (np.ndarray, nd.NDArray)):
            if y is None:
                if is_train:
                    raise ValueError("y must be specified when X is numpy.ndarray")
                y = np.zeros(X.shape[0] if hasattr(X, "shape") else len(X))
            batch_size = min(self.numpy_batch_size, X.shape[0])
            return mxio.NDArrayIter(X, y, batch_size=batch_size, shuffle=is_train, last_batch_handle="roll_over" if is_train else "pad")
        return X

    def fit(
        self,
        X,
        y=None,
        eval_data=None,
        eval_metric="acc",
        epoch_end_callback=None,
        batch_end_callback=None,
        kvstore="local",
        logger=None,
        work_load_list=None,
        monitor=None,
        eval_end_callback=None,
        eval_batch_end_callback=None,
    ):
        """(reference: model.py FeedForward.fit)"""
        from .module import Module

        data = self._init_iter(X, y, is_train=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = self._init_iter(eval_data[0], eval_data[1], is_train=False)

        label_names = [n for n in self.symbol.list_arguments() if n.endswith("label")]
        mod = Module(
            self.symbol,
            data_names=[d.name for d in data.provide_data],
            label_names=label_names,
            logger=logger or logging,
            context=self.ctx,
            work_load_list=work_load_list,
        )
        optimizer_params = dict(self.kwargs)
        if "learning_rate" not in optimizer_params and "lr" in optimizer_params:
            optimizer_params["learning_rate"] = optimizer_params.pop("lr")
        mod.fit(
            data,
            eval_data=eval_data,
            eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback,
            kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=tuple(optimizer_params.items()),
            initializer=self.initializer,
            arg_params=self.arg_params,
            aux_params=self.aux_params,
            allow_missing=True,
            begin_epoch=self.begin_epoch,
            num_epoch=self.num_epoch,
            monitor=monitor,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback,
        )
        self.arg_params, self.aux_params = mod.get_params()
        self._module = mod
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """(reference: model.py FeedForward.predict)"""
        data = self._init_iter(X, None, is_train=False)
        from .module import Module

        label_names = [n for n in self.symbol.list_arguments() if n.endswith("label")]
        mod = Module(
            self.symbol,
            data_names=[d.name for d in data.provide_data],
            label_names=label_names,
            context=self.ctx,
        )
        mod.bind(data.provide_data, data.provide_label or None, for_training=False)
        mod.init_params(arg_params=self.arg_params, aux_params=self.aux_params, allow_missing=True)
        outputs = mod.predict(data, num_batch=num_batch, always_output_list=True)
        if len(outputs) == 1:
            return outputs[0].asnumpy()
        return [o.asnumpy() for o in outputs]

    def score(self, X, eval_metric="acc", num_batch=None):
        data = self._init_iter(X, None, is_train=False)
        from .module import Module

        label_names = [n for n in self.symbol.list_arguments() if n.endswith("label")]
        mod = Module(self.symbol, data_names=[d.name for d in data.provide_data], label_names=label_names, context=self.ctx)
        mod.bind(data.provide_data, data.provide_label, for_training=False)
        mod.init_params(arg_params=self.arg_params, aux_params=self.aux_params, allow_missing=True)
        res = mod.score(data, eval_metric, num_batch=num_batch)
        return res[0][1]

    def save(self, prefix, epoch=None):
        """(reference: FeedForward.save)"""
        if epoch is None:
            epoch = self.num_epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params, self.aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """(reference: FeedForward.load)"""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(
            symbol, ctx=ctx, arg_params=arg_params, aux_params=aux_params, begin_epoch=epoch, **kwargs
        )

    @staticmethod
    def create(
        symbol,
        X,
        y=None,
        ctx=None,
        num_epoch=None,
        epoch_size=None,
        optimizer="sgd",
        initializer=None,
        eval_data=None,
        eval_metric="acc",
        epoch_end_callback=None,
        batch_end_callback=None,
        kvstore="local",
        logger=None,
        work_load_list=None,
        eval_end_callback=None,
        eval_batch_end_callback=None,
        **kwargs,
    ):
        """(reference: FeedForward.create)"""
        model = FeedForward(
            symbol,
            ctx=ctx,
            num_epoch=num_epoch,
            epoch_size=epoch_size,
            optimizer=optimizer,
            initializer=initializer,
            **kwargs,
        )
        model.fit(
            X,
            y,
            eval_data=eval_data,
            eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback,
            kvstore=kvstore,
            logger=logger,
            work_load_list=work_load_list,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback,
        )
        return model
