"""KVStore: key-value parameter synchronization.

Counterpart of the reference's KVStore stack (include/mxnet/kvstore.h:26-303,
src/kvstore/kvstore_local.h:22, python/mxnet/kvstore.py). Semantics kept:
``push`` reduces (sums) the per-device values of a key, then either applies the
updater (the optimizer) to the stored weight or replaces it; ``pull``
broadcasts the stored weight to every requested output
(kvstore_local.h:50-88).

Types:
  * ``local`` / ``device`` — single-process multi-device aggregation. On this
    backend both reduce on the source devices (XLA handles placement); the
    cpu-pinned-vs-gpu distinction of the reference's CommCPU/CommDevice
    (comm.h:61,200) is a no-op under PJRT unified memory management.
  * ``dist_tpu_sync`` (and the reference spellings ``dist_sync`` /
    ``dist_device_sync``) — SPMD data parallelism over a JAX mesh: Push's
    reduce becomes an all-reduce across chips riding ICI, rank/size come from
    the JAX runtime (SURVEY.md §2.4 TPU-native plan). The ps-lite
    server/scheduler roles are gone — in SPMD every process runs the same
    program, so the "server side" IS the local update.
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import optimizer as opt
from . import telemetry as _tm
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


def _nbytes(arrs) -> int:
    """Host-side byte count of one value list (telemetry only)."""
    import numpy as np

    return sum(int(a.size) * np.dtype(a.dtype).itemsize for a in arrs)


class KVStore:
    """(reference: python/mxnet/kvstore.py)"""

    def __init__(self, type_name: str):
        self._type = type_name
        self._store: Dict = {}
        self._updater: Optional[opt.Updater] = None
        self._optimizer = None
        self._bucket_engine = None  # dist comm engine (kvstore_bucket)
        self._sparse_engine = None  # row-sparse rounds (sparse/kvstore_sparse)
        # monolithic-path digest window (bucketed path: engine._rounds_done)
        self._verify_rounds_done = 0
        self._verify_check_rounds = None  # lazy MXNET_KVSTORE_CHECK_STEPS

    # ------------------------------------------------------------------ meta
    @property
    def type(self) -> str:
        return self._type

    @property
    def rank(self) -> int:
        """(reference: kvstore.h get_rank → jax.process_index)"""
        if "dist" in self._type:
            import jax

            return jax.process_index()
        return 0

    @property
    def num_workers(self) -> int:
        """(reference: kvstore.h get_group_size → jax.process_count)"""
        if "dist" in self._type:
            import jax

            return jax.process_count()
        return 1

    def num_dead_nodes(self, timeout=60.0, startup_grace=None) -> int:
        """Workers whose heartbeat went stale (reference:
        KVStore::get_num_dead_node, include/mxnet/kvstore.h:234-244, over
        ps-lite heartbeats scanned in kvstore_dist.h:158-167). Backed by the
        launcher's heartbeat-file protocol (dist.num_dead_nodes); 0 for
        single-process stores or when heartbeating is not configured. A
        worker that has not heartbeated YET counts as alive until
        ``startup_grace`` (default ``timeout``) seconds after job start."""
        if "dist" not in self._type:
            return 0
        from . import dist

        return dist.num_dead_nodes(timeout=timeout,
                                   startup_grace=startup_grace)

    # ------------------------------------------------------------------- api
    def init(self, key, value):
        """(reference: kvstore_local.h:40 Init). In dist mode the stored value
        is rank 0's — the reference only pushes init values from rank 0
        (kvstore_dist.h Init), so every worker starts from identical weights
        regardless of local RNG state."""
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("duplicate init of key %s" % k)
            self._store[k] = self._broadcast_rank0(v.copy())

    def push(self, key, value, priority=0):
        """Reduce values per key; apply updater or replace
        (reference: kvstore_local.h:50 Push).

        ``priority`` is REAL on the dist path (reference: kvstore.h Push's
        priority queues): pushes land in their static bucket slot
        (kvstore_bucket.BucketPlan, built from the first push round) and a
        bucket's compiled collective dispatches — non-blocking, JAX async —
        the moment its last slot fills, higher-priority buckets first when
        several are ready. ``update_params_on_kvstore`` emits pushes in
        reverse-topo order with ``priority=-index``, so last-layer gradients
        fly while the host is still issuing the shallow layers' pushes and
        ``pull`` blocks only on its own bucket (docs/PERF.md §11). Every
        worker must push the same keys in the same order — SPMD training
        does this by construction, and the engine hash-verifies it for the
        first MXNET_KVSTORE_CHECK_STEPS rounds. On non-dist stores priority
        remains advisory: XLA's async dispatch orders work by data
        dependency."""
        keys, grouped = _group_kv(key, value)
        for k in keys:
            if k not in self._store:
                raise MXNetError("key %s has not been inited" % k)
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            # counted after validation: a rejected push must not inflate
            # the traffic counters the byte-model comparisons read
            pushed = _nbytes(m for vals in grouped for m in vals)
            _tm.counter("kvstore.push_calls").inc()
            _tm.counter("kvstore.push_bytes").inc(pushed)
            sp = _tm.span("kvstore.push", nkeys=len(keys), bytes=pushed,
                          dist="dist" in self._type, priority=priority)
        with sp:
            keys, grouped = self._route_sparse(keys, grouped, priority)
            if not keys:
                return
            eng = self._engine()
            if eng is not None:
                # bucketed path never mutates the merged value: skip the
                # defensive copy (the pack executable does the copy+cast)
                merged_list = [self._reduce_local(vals, copy=False)
                               for vals in grouped]
                eng.push(keys, merged_list, priority)
                return
            merged_list = [self._reduce_local(vals) for vals in grouped]
            if "dist" in self._type:
                self._verify_push_round(keys)
                merged_list = self._allreduce_batch(merged_list)
            for k, merged in zip(keys, merged_list):
                if self._updater is not None:
                    self._updater(k, merged, self._store[k])
                else:
                    self._store[k] = merged

    def pull(self, key, out=None, priority=0):
        """Broadcast stored weight to outputs (reference: kvstore_local.h:75).
        On the bucketed dist path this blocks only on the requested keys' own
        buckets — other buckets' collectives stay in flight."""
        assert out is not None
        keys, grouped = _group_kv(key, out)
        for k in keys:
            if k not in self._store:
                raise MXNetError("key %s has not been inited" % k)
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            pulled = _nbytes(o for outs in grouped for o in outs)
            _tm.counter("kvstore.pull_calls").inc()
            _tm.counter("kvstore.pull_bytes").inc(pulled)
            sp = _tm.span("kvstore.pull", nkeys=len(keys), bytes=pulled)
        with sp:
            if self._bucket_engine is not None:
                self._bucket_engine.before_read(keys)
            for k, outs in zip(keys, grouped):
                local = self._store[k]
                for o in outs:
                    o[:] = local

    def _route_sparse(self, keys, grouped, priority):
        """Split row-sparse values out of a push round and run them through
        the sparse engine (index-union round + lazy update,
        sparse/kvstore_sparse.py); returns the remaining dense items.
        Sparse keys bypass the bucket plan entirely — which rows move
        changes every round, the opposite of the plan's fixed offsets."""
        from .sparse import RowSparseNDArray

        if not any(isinstance(v, RowSparseNDArray)
                   for vals in grouped for v in vals):
            return keys, grouped
        eng = self._sparse()
        dense_k, dense_g = [], []
        for k, vals in zip(keys, grouped):
            if isinstance(vals[0], RowSparseNDArray):
                merged = vals[0]
                for v in vals[1:]:  # local multi-device reduce: index merge
                    merged = merged + v
                eng.push(k, merged, priority=priority)
            else:
                dense_k.append(k)
                dense_g.append(vals)
        return dense_k, dense_g

    def _sparse(self):
        """Lazy row-sparse engine (works on local AND dist stores)."""
        if self._sparse_engine is None:
            from .sparse.kvstore_sparse import SparseEngine

            self._sparse_engine = SparseEngine(self)
        return self._sparse_engine

    def row_sparse_pull(self, key, row_ids, priority=0):
        """Pull only the requested rows of a key as a RowSparseNDArray
        (reference: kvstore.py row_sparse_pull / kvstore_dist.h
        PullRowSparseImpl) — the serving/eval-side complement of the sparse
        push: a huge sharded-out table never has to materialize densely on
        the consumer."""
        if key not in self._store:
            raise MXNetError("key %s has not been inited" % key)
        from .sparse import RowSparseNDArray, normalize_row_ids

        rows = normalize_row_ids(row_ids)
        stored = self._store[key]
        if _tm.enabled():
            _tm.counter("kvstore.pull_calls").inc()
            _tm.counter("kvstore.pull_bytes").inc(
                int(rows.size * int(np.prod(stored.shape[1:]) or 1)
                    * stored.dtype.itemsize))
        vals = stored._jax()[rows] if rows.size else \
            np.zeros((0,) + tuple(stored.shape[1:]), stored.dtype)
        return RowSparseNDArray(rows, NDArray(vals, ctx=stored.context),
                                stored.shape, ctx=stored.context)

    def _engine(self):
        """Lazy bucket engine for multi-process dist stores
        (MXNET_KVSTORE_BUCKET=0 opts back into the unbucketed batched
        collective, for A/B measurement)."""
        if self._bucket_engine is not None:
            return self._bucket_engine
        if "dist" not in self._type:
            return None
        import os

        if os.environ.get("MXNET_KVSTORE_BUCKET", "1").lower() in (
                "0", "off", "false"):
            return None
        import jax

        if jax.process_count() == 1:
            return None
        from .kvstore_bucket import BucketEngine

        self._bucket_engine = BucketEngine(self)
        return self._bucket_engine

    def _reduce_local(self, vals: List[NDArray], copy=True) -> NDArray:
        """Reduce this process's device copies of one key. ``copy=False``
        skips the defensive copy for consumers that only read the value
        (the store must never alias a caller-mutable NDArray)."""
        if len(vals) == 1:
            return vals[0].copy() if copy else vals[0]
        # tree-free single fused sum: one XLA add chain, fused on-device
        # (reference: comm.h ReduceSumCPU / CommDevice::Reduce)
        return nd.add_n(*vals, num_args=len(vals))

    def _broadcast_rank0(self, arr: NDArray) -> NDArray:
        """Every worker adopts rank 0's value (dist init parity)."""
        if "dist" not in self._type:
            return arr
        import jax

        if jax.process_count() == 1:
            return arr
        from jax.experimental.multihost_utils import broadcast_one_to_all

        return NDArray(broadcast_one_to_all(arr._jax()), ctx=arr.context)

    def _allreduce_batch(self, arrs: List[NDArray]) -> List[NDArray]:
        """Cross-process all-reduce of one push round as ONE compiled
        collective per dtype: flatten-concat all keys, psum over a
        process-spanning mesh, split back. Replaces the round-2 per-key
        host allgather (O(workers·size) over DCN through host memory) with
        an XLA reduction riding ICI/DCN."""
        import jax

        if jax.process_count() == 1:
            return arrs
        coll = _Collective.get()
        # one collective per dtype keeps the concat homogeneous
        by_dtype: Dict = {}
        for i, a in enumerate(arrs):
            by_dtype.setdefault(str(a.dtype), []).append(i)
        out: List = [None] * len(arrs)
        for idxs in by_dtype.values():
            flats = [arrs[i]._jax().reshape(-1) for i in idxs]
            summed = coll.allreduce_concat(flats)
            off = 0
            for i in idxs:
                n = arrs[i].size
                out[i] = NDArray(
                    summed[off:off + n].reshape(arrs[i].shape),
                    ctx=arrs[i].context)
                off += n
        return out

    # ------------------------------------------------------------ validation
    def _verify_push_round(self, keys):
        """Monolithic-path twin of the bucket engine's first-N round check:
        before the fused allreduce, allgather a 4-byte digest of this
        round's key order so rank-dependent pushes fail loudly instead of
        deadlocking (or silently misreducing) inside the collective. The
        window re-arms via ``rearm_verify()``/``reform()``."""
        import jax

        if jax.process_count() == 1:
            return
        from .kvstore_bucket import (BucketEngine,
                                     verify_digest_across_workers)

        if self._verify_check_rounds is None:
            self._verify_check_rounds = BucketEngine._env_check_rounds()
        self._verify_rounds_done += 1
        if self._verify_rounds_done > self._verify_check_rounds:
            return
        verify_digest_across_workers(repr(list(keys)),
                                     self._verify_check_rounds,
                                     BucketEngine._allgather_digest)

    def rearm_verify(self):
        """Re-open the collective key-sequence digest window (both the
        bucketed and monolithic push paths) after anything that can
        desynchronize the workers' push streams — an elastic ``reform``, a
        bucket plan change, a manual topology intervention. The next
        MXNET_KVSTORE_CHECK_STEPS rounds verify again."""
        self._verify_rounds_done = 0
        if self._bucket_engine is not None:
            self._bucket_engine.rearm_verify()

    # -------------------------------------------------------------- optimizer
    def set_optimizer(self, optimizer):
        """(reference: kvstore.py:232 set_optimizer; in dist mode the reference
        pickles the optimizer to the servers — SPMD has no servers, the updater
        runs in-process on every worker over all-reduced gradients.)"""
        self._optimizer = optimizer
        self._set_updater(opt.get_updater(optimizer))

    def _set_updater(self, updater):
        self._updater = updater

    def _barrier(self):
        """(reference: kvstore.h Barrier) — collective barrier across workers."""
        if "dist" in self._type:
            import jax

            if self._bucket_engine is not None:
                # drain in-flight bucket collectives before the sync point
                self._bucket_engine.finalize_all()
            if jax.process_count() > 1:
                from jax.experimental.multihost_utils import sync_global_devices

                sync_global_devices("mxnet_tpu_kvstore_barrier")

    def get_num_dead_node(self, node_id=0, timeout=None):
        """Dead-worker count (reference: kvstore.h:234-244 — a ps-lite
        heartbeat scan, meaningful because that topology tolerated dead
        workers). Under the default gang-scheduled runtime (SURVEY.md §5.3)
        the JAX coordination service heartbeats every process itself and a
        dead peer aborts the whole job rather than leaving it degraded, so
        while this process runs the worker set is by construction fully
        live — return 0; recovery is checkpoint-resume. Under
        ``MXNET_ELASTIC=1`` (docs/FAULT_TOLERANCE.md) death propagation is
        disabled and membership is OURS to track: the heartbeat-file scan
        is authoritative, exactly the reference's ps-lite semantics.

        ``timeout`` defaults to ``MXNET_ELASTIC_DEAD_TIMEOUT`` (60 s) —
        NOT the reference's 3 s, which was tuned to ps-lite's 1 s beat and
        would class ~half of the live workers dead against this port's
        default 5 s heartbeat interval."""
        from . import dist

        if "dist" in self._type and dist.elastic_enabled():
            if timeout is None:
                return dist.num_dead_nodes(
                    timeout=dist.dead_timeout_seconds())
            return dist.num_dead_nodes(timeout=timeout)
        return 0

    def save_optimizer_states(self, fname):
        """Persist optimizer state. Replicated/local: the per-key Updater
        state pickle, written atomically (temp + os.replace). Sharded
        (MXNET_KVSTORE_UPDATE=sharded): each worker writes its 1/W flat
        shard to ``<fname>.sharded/step-<N>/`` plus a digest-guarded
        manifest, and ``fname`` itself becomes a small pointer file — the
        format load_optimizer_states resolves for both same-W (shard-direct,
        momentum bit-parity) and different-W (re-flattened) resume
        (docs/FAULT_TOLERANCE.md)."""
        assert self._updater is not None, "Cannot save states for distributed training"
        from . import checkpoint as ckpt

        eng = self._bucket_engine
        # ONLY the flat-sharded engine takes the pointer-file path: sparse
        # tables ride its shard files there (Checkpointer._collect_sparse).
        # A replicated/local store — sparse keys or not — keeps the classic
        # per-key state pickle, which carries RowSparseState as plain numpy
        # (a sparse-only branch here once silently DROPPED every dense
        # key's state; regression-tested in test_sparse_checkpoint.py).
        if eng is not None and eng._sharded_state:
            eng.finalize_all()
            opt = self._optimizer
            step = int(opt.num_update) if opt is not None else 0
            # ephemeral writer, closed after the (blocking) save: fname is
            # epoch-numbered under module_checkpoint, so caching per path
            # would never hit and each epoch would leak an idle daemon
            # writer thread
            writer = ckpt.Checkpointer(fname + ".sharded")
            try:
                writer.save_sharded(self, step, block=True)
            finally:
                writer.close()
            import json

            ckpt.atomic_write_bytes(fname, json.dumps(
                {"format": "mxtpu-sharded-states",
                 "dir": fname + ".sharded", "step": step}).encode())
            return
        ckpt.atomic_write_bytes(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        """Inverse of save_optimizer_states. A sharded pointer file loads
        through mxnet_tpu.checkpoint: when the live bucket plan and world
        match the manifest, this worker's shard file device_puts straight
        into the flat state (bit-parity); otherwise the shard set is
        re-flattened into per-key Updater states on the host and the engine
        re-shards them under its own plan (different-W resume). Optimizer
        update counts are restored from the manifest either way. A torn or
        corrupt file raises a structured MXNetError naming the path."""
        assert self._updater is not None, "Cannot load states for distributed training"
        from . import checkpoint as ckpt

        pointer = ckpt.read_sharded_pointer(fname)
        if pointer is not None:
            self._load_sharded_states(fname, pointer)
            return
        with open(fname, "rb") as fin:
            blob = fin.read()
        try:
            self._updater.set_states(blob)
        except Exception as e:
            raise MXNetError(
                "optimizer-state file %r is torn or not a state pickle "
                "(%s: %s) — likely a crash mid-save; delete it and resume "
                "from the previous checkpoint"
                % (fname, type(e).__name__, e)) from e
        if self._bucket_engine is not None:
            # flat shards (if any) must re-seed from the freshly loaded
            # per-key states, not keep pre-load momentum
            self._bucket_engine.reseed_updater_states()

    def _load_sharded_states(self, fname, pointer):
        from . import checkpoint as ckpt

        root, step = pointer["dir"], pointer["step"]
        manifest = ckpt.load_manifest(root, step)
        if manifest is None:
            raise MXNetError(
                "sharded optimizer-state pointer %r names step %s in %r but "
                "no readable manifest exists there — the checkpoint set is "
                "torn or was deleted" % (fname, step, root))
        self._check_flat_spec(manifest, fname)
        self._seed_states_from_manifest(root, step, manifest)

    def _check_flat_spec(self, manifest, path):
        """The live optimizer must lower to the same flat kernel family as
        the one that wrote the checkpoint — states are not portable across
        optimizer kinds."""
        opt = self._optimizer
        if opt is None:
            return
        kind, _, n_states = opt.flat_update_spec() or (None, None, None)
        want = manifest["optimizer"]
        if kind is not None and (want["kind"] != kind
                                 or want["n_states"] != n_states):
            raise MXNetError(
                "sharded optimizer states at %r were saved by a %r "
                "optimizer (%d state slots); the live optimizer %s "
                "lowers to %r (%d slots) — states are not portable "
                "across optimizer kinds"
                % (path, want["kind"], want["n_states"],
                   type(opt).__name__, kind, n_states))

    def _seed_states_from_manifest(self, root, step, manifest, flats=None,
                                   sparse_tables=None):
        """Seed optimizer state from a sharded checkpoint step: shard-direct
        when the live plan/world match (momentum bit-parity), else re-flatten
        every worker's shard into per-key Updater states (different-W
        resume). Update counts restore from the manifest either way.

        At FIT-START resume no plan is committed yet (it commits on the
        first push round), so even a same-W resume takes the re-flatten
        path — which costs nothing extra there: ``load_sharded_checkpoint``
        must read every shard file anyway to reconstruct the full WEIGHTS
        (they are sharded 1/W per file too), and re-flatten is pure
        concatenate/slice — bit-lossless (tested:
        test_same_world_fit_resume_bit_parity)."""
        from . import checkpoint as ckpt

        eng = self._bucket_engine
        import jax

        same_world = manifest["world"] == jax.process_count()
        if (eng is not None and eng.plan is not None and same_world
                and eng.mode == "sharded"
                and eng.plan.hash == manifest.get("plan_hash")):
            # the mode check matters: an engine downgraded to replicated
            # (partial-push veto) never consumes _preloaded_shards — the
            # re-flatten path below seeds _updater.states, which replicated
            # updates actually read
            # shard-direct: this worker's own shard seeds its flat slices
            # verbatim — no re-flatten, momentum bit-parity
            n_states = manifest["optimizer"]["n_states"]
            if flats is not None:
                # the caller already read + digest-verified EVERY shard
                # (read_flat_buckets); slice our rows back out instead of
                # paying a second read + sha256 of our own shard file
                world = int(manifest["world"])
                shards = {}
                for b in manifest["plan"]["buckets"]:
                    idx = int(b["index"])
                    sliced = []
                    for s in flats[idx]["states"]:
                        n = s.shape[0] // world
                        sliced.append(s[self.rank * n:(self.rank + 1) * n])
                    shards[idx] = sliced
            else:
                local = ckpt.read_local_shard(root, step, manifest,
                                              self.rank)
                shards = {
                    int(b["index"]): [local["b%d.s%d"
                                            % (int(b["index"]), i)]
                                      for i in range(n_states)]
                    for b in manifest["plan"]["buckets"]}
            eng.preload_flat_shards(shards)
        else:
            if flats is None:
                flats = ckpt.read_flat_buckets(root, step, manifest)
            states = ckpt.per_key_states(manifest, flats)
            from .ndarray import NDArray
            import jax.numpy as jnp

            for key, tup in states.items():
                nds = tuple(NDArray(jnp.asarray(a)) for a in tup)
                self._updater.states[key] = (
                    nds[0] if len(nds) == 1 else nds if nds else None)
            if eng is not None:
                eng.reseed_updater_states()
        self._seed_sparse_states(root, step, manifest, tables=sparse_tables)
        opt = self._optimizer
        if opt is not None:
            for key, count in manifest.get("update_counts", ()):
                opt._index_update_count[key] = int(count)
            opt.num_update = max(opt.num_update,
                                 int(manifest.get("num_update", 0)))

    def _seed_sparse_states(self, root, step, manifest, tables=None):
        """Seed row-sparse optimizer states from the manifest's sparse
        section (index+rows per shard, docs/SPARSE.md) — re-assembled by
        concatenation, so ANY reader world resumes bit-identically from
        any writer world. ``tables`` reuses an already-read shard set."""
        from . import checkpoint as ckpt

        if not manifest.get("sparse"):
            return
        from .sparse import RowSparseState

        if tables is None:
            tables = ckpt.read_sparse_tables(root, step, manifest)
        for row in manifest["sparse"]:
            key = row["key"]
            t = tables[key]
            st = RowSparseState(tuple(row["shape"]), row["dtype"],
                                int(row["n_states"]))
            st.indices = t["indices"]
            st.rows = [np.asarray(s, st.dtype) for s in t["states"]]
            self._updater.states[key] = st

    # ---------------------------------------------------------------- elastic
    #
    # The pause/re-form/resume state machine (docs/FAULT_TOLERANCE.md):
    #
    #     running --(pause decision agreed)--> paused
    #     paused  --(dist.reform succeeded)--> reforming
    #     reforming --(weights/state reseeded)--> resuming
    #     resuming --(first post-re-form round)--> running
    #
    # Driven by module.elastic.ElasticFit; surfaced here because the store
    # is what every training loop already holds a handle to. Unrecoverable
    # transitions (coordinator death, below-min survivors, no checkpoint)
    # raise structured MXNetErrors from the dist/checkpoint layers.

    _ELASTIC_STATES = ("running", "paused", "reforming", "resuming")

    @property
    def elastic_state(self) -> str:
        """Where this store is in the elastic state machine; ``running``
        outside a recovery window (and always, for non-elastic jobs)."""
        return getattr(self, "_elastic_state", "running")

    def _set_elastic_state(self, state):
        assert state in self._ELASTIC_STATES, state
        self._elastic_state = state
        if _tm.enabled():
            _tm.event("kvstore.elastic_state", state=state)
            _tm.gauge("kvstore.elastic_paused").set(
                0 if state == "running" else 1)

    def _reseed(self, key, value):
        """Overwrite one stored weight (recovery path: ``init`` refuses
        duplicates by design, but a re-formed worker reseeding from a
        checkpoint must replace)."""
        if key not in self._store:
            raise MXNetError("cannot reseed key %s before init" % key)
        self._store[key] = value.copy()

    def reform(self):
        """Re-form this store over the CURRENT (post-recovery) process set:
        rebuild the compiled collective layer and re-plan the bucket engine
        for the new worker count. The caller (dist.reform via the elastic
        controller, docs/FAULT_TOLERANCE.md) has already rebuilt the JAX
        backend over the survivors; store values and optimizer state must be
        reseeded afterwards — they referenced the old backend's buffers."""
        if "dist" not in self._type:
            return
        self._set_elastic_state("reforming")
        _Collective._cache = None  # stale worker mesh must not survive
        if self._bucket_engine is not None:
            self._bucket_engine.reform()
        # survivors must re-prove push-stream agreement over the new world
        self.rearm_verify()

    def load_sharded_checkpoint(self, root, step=None):
        """Seed stored WEIGHTS and optimizer state from a sharded
        checkpoint set under ``root`` (docs/FAULT_TOLERANCE.md): the
        recovery path after an elastic re-form, and the cold-start path for
        a job relaunched at a different world size. ``step=None`` resolves
        the newest COMPLETE step. Weight keys must already be inited (the
        training loop binds before it recovers). Returns ``(step,
        weights)`` with ``weights`` mapping key -> host np array so the
        caller (Module's recovery hook) can adopt them into its executors.

        Raises a structured ``MXNetError`` when no complete checkpoint
        exists, the manifest is for a different optimizer family, or the
        shard set fails its digest check."""
        from . import checkpoint as ckpt

        if step is None:
            got = ckpt.latest_complete(root)
            if got is None:
                raise MXNetError(
                    "no COMPLETE sharded checkpoint under %r — nothing to "
                    "recover from (a torn/in-flight step does not count)"
                    % (root,))
            step, manifest = got
        else:
            manifest = ckpt.load_manifest(root, step)
            if manifest is None:
                raise MXNetError(
                    "checkpoint step %s under %r has no readable manifest"
                    % (step, root))
        if manifest.get("kind") != "sharded":
            raise MXNetError(
                "checkpoint step %s under %r is %r, not a sharded set"
                % (step, root, manifest.get("kind")))
        self._check_flat_spec(manifest, root)
        with _tm.span("checkpoint.load", step=step,
                      world=manifest.get("world")):
            # ONE disk + sha256 pass over the shard set; flats, sparse
            # tables and the state seeding below all slice from it
            shards = ckpt.read_shard_set(root, step, manifest)
            flats = ckpt.read_flat_buckets(root, step, manifest,
                                           shards=shards)
            weights = ckpt.per_key_states(manifest, flats, weights=True)
            # row-sparse tables: the full dense table re-assembles from the
            # per-worker 1/W pieces (docs/SPARSE.md)
            sparse_tables = ckpt.read_sparse_tables(root, step, manifest,
                                                    shards=shards)
            for key, t in sparse_tables.items():
                weights[key] = t["w"]
            from .ndarray import NDArray
            import jax.numpy as jnp

            for key, w in weights.items():
                if key in self._store:
                    self._store[key] = NDArray(jnp.asarray(w))
            self._seed_states_from_manifest(root, step, manifest,
                                            flats=flats,
                                            sparse_tables=sparse_tables)
        return step, weights


class _Collective:
    """Compiled cross-process collectives for the dist KVStore.

    The mesh holds ONE device per process (the KVStore reduce is a
    per-process quantity — local device copies are already summed), so each
    process's contribution is exactly one row of a ``(num_workers, n)``
    global array, assembled zero-copy from the local device buffer. A jitted
    replicated-output sum over axis 0 is the sum over workers, and XLA
    lowers it to an all-reduce riding ICI/DCN."""

    _cache = None  # (key, instance) for the CURRENT backend only

    @classmethod
    def get(cls):
        # keyed on backend identity + device topology: a second KVStore after
        # a mesh/backend change (including an in-process backend restart with
        # identical topology) must not reuse a stale worker mesh. Exactly one
        # entry is kept — superseded backends (and their meshes/executables)
        # are released, which also keeps the id()-based key collision-free.
        import jax

        devs = jax.devices()
        key = (id(devs[0].client),
               tuple(sorted((d.process_index, d.id) for d in devs)))
        if cls._cache is None or cls._cache[0] != key:
            cls._cache = (key, cls())
        return cls._cache[1]

    def __init__(self):
        import functools

        import jax
        import numpy as np_
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        # first device of every process, in process order
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = [by_proc[p] for p in sorted(by_proc)]
        self.n_workers = len(devs)
        self.rank = jax.process_index()
        self.my_device = by_proc[jax.process_index()]
        self.mesh = Mesh(np_.array(devs), ("worker",))
        self.row_sharding = NamedSharding(self.mesh, P("worker"))

        # row-sharded input + replicated output: the partitioner lowers the
        # axis-0 sum to an all-reduce over the worker axis (measured faster
        # than an explicit shard_map psum on the gloo CPU backend, and
        # equivalent on ICI). Accumulation runs in ``acc_dtype`` (fp32 for
        # bf16-compressed wire buffers, MXNET_KVSTORE_COMM_DTYPE) — one
        # jitted callable per accumulate dtype, shape/dtype specialization
        # is jit's own cache.
        self._sum_rows_cache = {}

        def _make_sum(acc):
            import jax.numpy as jnp

            acc_dt = jnp.dtype(acc) if acc else None

            @functools.partial(
                jax.jit, out_shardings=NamedSharding(self.mesh, P()))
            def _sum_rows(x):
                if acc_dt is not None and x.dtype != acc_dt:
                    x = x.astype(acc_dt)
                return x.sum(axis=0)

            return _sum_rows

        self._make_sum = _make_sum
        self._sum_rows = _make_sum(None)

    def make_global_rows(self, row):
        """Zero-copy (W, n) global array from this process's (1, n) row.

        Injection site ``dist.collective`` (docs/RESILIENCE.md): every
        kvstore allreduce/reduce-scatter assembles its global array here,
        so one seam covers the whole collective surface — a `raise` makes
        this worker's collective fail exactly the way a dead peer's
        transport error does (the elastic recovery trigger), a delay
        models a straggler."""
        import jax

        from . import faultinject as _fi

        _fi.fire("dist.collective")
        return jax.make_array_from_single_device_arrays(
            (self.n_workers,) + tuple(row.shape[1:]), self.row_sharding,
            [row])

    def allreduce_rows(self, row, acc_dtype=None):
        """All-reduce this process's (1, n) row against its peers; returns
        the summed (n-vector as a) fully-replicated global array — kept ON
        DEVICE (callers slice lazily via ``.addressable_data(0)``)."""
        key = str(acc_dtype) if acc_dtype is not None else None
        fn = self._sum_rows_cache.get(key)
        if fn is None:
            fn = self._make_sum(key)
            self._sum_rows_cache[key] = fn
        return fn(self.make_global_rows(row))

    def allreduce_concat(self, flats):
        """All-reduce the concatenation of 1-D arrays; returns the summed
        flat array as a single-device jax array, ON DEVICE — the earlier
        ``jnp.asarray(...)`` here forced a host copy of the full replicated
        result (device→host→device per round); callers slice straight from
        the device buffer now."""
        import jax
        import jax.numpy as jnp

        flat = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
        row = jax.device_put(flat.reshape(1, -1), self.my_device)
        out = self._sum_rows(self.make_global_rows(row))
        return out.addressable_data(0)


def _key_value(key, value):
    if isinstance(key, (list, tuple)):
        assert isinstance(value, (list, tuple)) and len(key) == len(value)
        return list(key), list(value)
    return [key], [value]


def _group_kv(key, value):
    """Group possibly-duplicate keys with per-device value lists
    (reference: kvstore_local.h:95 GroupKVPairs)."""
    if isinstance(key, (list, tuple)):
        if len(key) and isinstance(value, (list, tuple)) and len(value) == len(key) and not isinstance(value[0], (list, tuple)):
            return list(key), [[v] for v in value]
        assert len(key) == len(value)
        return list(key), [list(v) if isinstance(v, (list, tuple)) else [v] for v in value]
    if isinstance(value, (list, tuple)):
        return [key], [list(value)]
    return [key], [[value]]


def create(name="local") -> KVStore:
    """Create a KVStore (reference: kvstore.py create / kvstore.cc:17-45).
    ``dist_sync``/``dist_device_sync`` map onto ``dist_tpu_sync`` — the SPMD
    collective design replaces the parameter-server topology."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    known = ("local", "device", "local_allreduce_cpu", "local_allreduce_device",
             "dist_tpu_sync", "dist_sync", "dist_device_sync", "dist_async")
    if name not in known:
        raise MXNetError("unknown KVStore type %r (known: %s)" % (name, known))
    if name == "dist_async":
        import logging

        logging.warning(
            "KVStore 'dist_async' runs as SYNCHRONOUS all-reduce here: the "
            "SPMD collective design has no parameter server to absorb stale "
            "updates. Convergence semantics are those of dist_sync.")
    if "dist" in name:
        # join the job's coordination service if tools/launch.py spawned us
        # (reference: KVStore::InitPSEnv consuming the DMLC_* cluster env)
        from . import dist

        dist.init()
    return KVStore(name)
