"""Training-side C ABI: build helper + the Python glue the embedded
interpreter calls (src/c_api.cc; reference: include/mxnet/c_api.h's
imperative slice, src/c_api/c_api_ndarray.cc:322 MXImperativeInvoke).

The C library addresses everything through this module so the C side stays
a thin GIL/refcount shim: op invocation (by registry name, string attrs
parsed exactly like symbol JSON), simple_bind over a symbol JSON, KVStore
verbs, and host copies."""
from __future__ import annotations

import os
import sys
import sysconfig
import threading

import numpy as np

from ._native_build import build_lib, source_path

__all__ = ["build", "lib_path"]

_SRC = source_path("c_api.cc")
_lock = threading.Lock()


def lib_path():
    from ._native_build import _BUILD_DIR

    return os.path.join(_BUILD_DIR, "libmxtpu_c.so")


def build(force=False):
    """Compile (if stale) and return the .so path; None if no toolchain."""
    with _lock:
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR")
        pyver = "python%d.%d" % sys.version_info[:2]
        return build_lib(_SRC, "libmxtpu_c.so", force=force,
                         extra_flags=["-I", inc, "-L", libdir, "-l", pyver])


# ---------------------------------------------------------------- C-side glue
def zeros(shape):
    from . import ndarray as nd

    return nd.zeros(tuple(int(d) for d in shape))


def copy_from_host(arr, mem):
    # .copy() is load-bearing: jax's CPU backend zero-copy-aliases numpy
    # arrays, and the C caller frees its buffer right after this returns
    # (same reason predict_api.cc's make_array copies)
    data = np.frombuffer(mem, dtype=np.float32).reshape(arr.shape).copy()
    arr[:] = data
    return True


def waitall():
    from . import ndarray as nd

    nd.waitall()
    return True


def invoke(op_name, inputs, keys, vals, outs):
    """MXImperativeInvokeByName glue: string attr values, optional in-place
    ``out=`` targets. Returns the output list (possibly the out targets)."""
    from . import ndarray as nd
    from .ops.registry import get_op, parse_attrs

    attrs = dict(zip(keys, vals))
    if outs is not None:
        # imperative_invoke zip-truncates; an undersized out list would
        # silently drop outputs (e.g. sgd_mom_update's momentum) — refuse
        opdef = get_op(op_name)
        n_out = opdef.num_outputs(parse_attrs(opdef, dict(attrs)))
        if len(outs) != n_out:
            raise ValueError(
                "%s produces %d outputs but %d out targets were supplied"
                % (op_name, n_out, len(outs)))
    res = nd.imperative_invoke(op_name, list(inputs), attrs,
                               out=list(outs) if outs is not None else None)
    return list(res)


def bind_from_json(symbol_json, shapes):
    from . import symbol as sym
    from .context import current_context

    net = sym.load_json(symbol_json)
    # the named inputs (data/labels — the keys the C caller gave shapes
    # for) get grad_req null so MXExecutorGetGrad returns NULL for them,
    # per the header's parameter-vs-input idiom; everything else is a
    # trainable parameter with grad_req write
    grad_req = {n: ("null" if n in shapes else "write")
                for n in net.list_arguments()}
    ex = net.simple_bind(current_context(), grad_req=grad_req,
                         **{k: tuple(v) for k, v in shapes.items()})
    return ex


def arg_names(ex):
    return list(ex.arg_dict.keys())


def get_arg(ex, name):
    if name not in ex.arg_dict:
        raise KeyError("unknown argument %r" % name)
    return ex.arg_dict[name]


def get_grad(ex, name):
    if name not in ex.grad_dict:
        raise KeyError("unknown argument %r" % name)
    return ex.grad_dict[name]


def kv_create(type_str):
    from . import kvstore

    return kvstore.create(type_str)


def kv_init(kv, keys, vals):
    kv.init(list(keys), list(vals))
    return True


def kv_push(kv, keys, vals):
    kv.push(list(keys), list(vals))
    return True


def kv_pull(kv, keys, outs):
    kv.pull(list(keys), out=list(outs))
    return True
