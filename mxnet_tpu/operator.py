"""Custom operator user API (reference: python/mxnet/operator.py).

Define a ``CustomOp`` + ``CustomOpProp`` pair, register it, then use it as
``mx.nd.Custom(..., op_type=name)`` or ``mx.sym.Custom(..., op_type=name)``.
The execution mechanism lives in ops/custom.py (pure_callback into the traced
program instead of the reference's C-callback engine ops).
"""
from __future__ import annotations

from .ops.custom import register_custom as register  # noqa: F401

__all__ = ["CustomOp", "CustomOpProp", "register"]


class CustomOp:
    """Base class for user operators (reference: operator.py:396 CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """(reference: operator.py CustomOp.assign)"""
        if req in ("null",):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp:
    """Base class describing a custom op (reference: operator.py:472
    CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()
