"""RNN toolkit: composable recurrent cells + bucketing data iterator.

Counterpart of the reference's python/mxnet/rnn package (rnn_cell.py:90
BaseRNNCell, :497 FusedRNNCell; io.py:61 BucketSentenceIter)."""
from .rnn_cell import (
    RNNParams,
    BaseRNNCell,
    RNNCell,
    LSTMCell,
    GRUCell,
    FusedRNNCell,
    SequentialRNNCell,
    BidirectionalCell,
    DropoutCell,
    ModifierCell,
    ZoneoutCell,
    ResidualCell,
)
from .io import BucketSentenceIter

__all__ = [
    "RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
    "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
    "ModifierCell", "ZoneoutCell", "ResidualCell", "BucketSentenceIter",
]
