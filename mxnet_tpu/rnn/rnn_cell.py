"""Recurrent cells over the Symbol layer.

Counterpart of the reference's python/mxnet/rnn/rnn_cell.py. The unfused
cells (RNNCell/LSTMCell/GRUCell) build one timestep of symbol graph and
``unroll`` composes seq_len of them — the reference's unrolled-in-time
strategy (rnn_cell.py:90-316). ``FusedRNNCell`` instead lowers the whole
sequence to the registry's ``RNN`` op — a ``lax.scan`` the way the reference's
FusedRNNCell lowered to the cuDNN RNN op (rnn_cell.py:497) — and ``unfuse()``
converts back. Gate orders match the fused op's packed layout
(ops/rnn.py:_cell_step: LSTM i,f,g,o; GRU r,z,n), so ``unpack_weights`` /
``pack_weights`` round-trip between the two layouts.
"""
from __future__ import annotations

import numpy as np

from .. import symbol as sym
from ..base import MXNetError
from ..ops.rnn import rnn_param_size

__all__ = [
    "RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
    "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
    "ModifierCell", "ZoneoutCell", "ResidualCell",
]


class RNNParams:
    """Container for cell parameter variables (reference: rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell (reference: rnn_cell.py:90)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_shape(self):
        """Per-state shapes with 0 for the batch axis."""
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_size=0, **kwargs):
        """Initial state symbols. With ``batch_size`` > 0 these are concrete
        zeros; otherwise they are input Variables (the bucketing iterators
        feed them as data, example/rnn/lstm_bucketing.py init_states).

        Contract note (deliberate deviation from the reference): the zeros
        are shaped with batch extent **1**, not ``batch_size``, so one symbol
        serves any global batch — per-device slicing and sharded SPMD traces
        both split the batch after graph construction, and a baked batch
        extent would pin the graph to one world size. The cells consume
        states only through broadcasting ops, so the math is unchanged. If
        you need full-batch initial states in a non-broadcasting op (concat
        with the batch axis, etc.), pass ``batch_size=0`` and feed the state
        Variables as data instead."""
        assert not self._modified, "After applying modifier cells the base cell cannot be called directly."
        states = []
        for shape in self.state_shape:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None:
                states.append(func(name=name, **kwargs))
            elif batch_size:
                # batch axis 1, not batch_size: the zeros only enter the cell
                # through broadcasting elementwise ops, and a baked batch
                # extent would pin the symbol to one global batch — breaking
                # per-device slicing and sharded SPMD traces alike
                full = (1,) + tuple(shape[1:])
                states.append(sym._zeros(shape=full, name=name))
            else:
                states.append(sym.Variable(name))
        return states

    # ---------------------------------------------------- weight conversion
    def unpack_weights(self, args):
        """Split fused parameter blobs into per-gate weights (reference:
        rnn_cell.py unpack_weights). Base cells store weights unfused: no-op."""
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    # --------------------------------------------------------------- unroll
    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=False):
        """Unroll the cell ``length`` timesteps (reference: rnn_cell.py:90
        BaseRNNCell.unroll)."""
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [sym.Variable("%st%d_data" % (input_prefix, i)) for i in range(length)]
        elif isinstance(inputs, sym.Symbol):
            if len(inputs) != 1:
                raise MXNetError("unroll expects a single-output Symbol or a list")
            inputs = list(sym.SliceChannel(inputs, axis=axis, num_outputs=length, squeeze_axis=1))
        else:
            inputs = list(inputs)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [sym.expand_dims(o, axis=axis) for o in outputs]
            outputs = sym.Concat(*outputs, dim=axis, num_args=length)
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell, tanh or relu (reference: rnn_cell.py:317 RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden, name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden, name="%sh2h" % name)
        if self._activation == "relu":
            output = sym.Activation(i2h + h2h, act_type="relu", name="%sout" % name)
        else:
            output = sym.Activation(i2h + h2h, act_type="tanh", name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference: rnn_cell.py:365 LSTMCell). Gate order i,f,g,o —
    identical to the fused RNN op's packed layout."""

    def __init__(self, num_hidden, prefix="lstm_", params=None, forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from ..initializer import LSTMBias

        self._iB = self.params.get("i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden), (0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 4, name="%si2h" % name)
        h2h = sym.FullyConnected(data=states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 4, name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = sym.SliceChannel(gates, num_outputs=4, name="%sslice" % name)
        in_gate = sym.Activation(slice_gates[0], act_type="sigmoid", name="%si" % name)
        forget_gate = sym.Activation(slice_gates[1], act_type="sigmoid", name="%sf" % name)
        in_transform = sym.Activation(slice_gates[2], act_type="tanh", name="%sc" % name)
        out_gate = sym.Activation(slice_gates[3], act_type="sigmoid", name="%so" % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym.Activation(next_c, act_type="tanh", name="%sstate" % name)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference: rnn_cell.py:430 GRUCell). Gate order r,z,n with
    separate i2h/h2h biases — the fused (cuDNN-convention) layout."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_shape(self):
        return [(0, self._num_hidden)]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = sym.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 3, name="%si2h" % name)
        h2h = sym.FullyConnected(data=prev_h, weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 3, name="%sh2h" % name)
        i2h_r, i2h_z, i2h_n = list(sym.SliceChannel(i2h, num_outputs=3, name="%si2h_slice" % name))
        h2h_r, h2h_z, h2h_n = list(sym.SliceChannel(h2h, num_outputs=3, name="%sh2h_slice" % name))
        reset_gate = sym.Activation(i2h_r + h2h_r, act_type="sigmoid", name="%sr" % name)
        update_gate = sym.Activation(i2h_z + h2h_z, act_type="sigmoid", name="%sz" % name)
        next_h_tmp = sym.Activation(i2h_n + reset_gate * h2h_n, act_type="tanh", name="%sh" % name)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-sequence fused cell over the registry's RNN op
    (reference: rnn_cell.py:497 FusedRNNCell → cuDNN RNN)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm", bidirectional=False,
                 dropout=0.0, get_next_state=False, forget_bias=1.0,
                 prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._parameter = self.params.get("parameters")
        self._directions = ["l", "r"] if bidirectional else ["l"]

    @property
    def state_shape(self):
        d = 2 if self._bidirectional else 1
        n = self._num_layers * d
        shapes = [(n, 0, self._num_hidden)]
        if self._mode == "lstm":
            shapes.append((n, 0, self._num_hidden))
        return shapes

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"), "gru": ("_r", "_z", "_o")}[self._mode]

    def _num_gates(self):
        return len(self._gate_names)

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped — use unroll, or unfuse()")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=True):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            raise MXNetError("FusedRNNCell.unroll requires inputs")
        if isinstance(inputs, (list, tuple)):
            inputs = sym.Concat(*[sym.expand_dims(i, axis=axis) for i in inputs],
                                dim=axis, num_args=length)
        if layout == "NTC":
            inputs = sym.SwapAxis(inputs, dim1=0, dim2=1, name="%sttmajor" % self._prefix)
        elif layout != "TNC":
            raise MXNetError("unknown layout %r" % layout)
        if begin_state is None:
            begin_state = self.begin_state()
        states = list(begin_state)
        kw = {"state": states[0]}
        if self._mode == "lstm":
            kw["state_cell"] = states[1]
        rnn = sym.RNN(data=inputs, parameters=self._parameter,
                      mode=self._mode, state_size=self._num_hidden,
                      num_layers=self._num_layers,
                      bidirectional=self._bidirectional, p=self._dropout,
                      state_outputs=self._get_next_state,
                      name="%srnn" % self._prefix, **kw)
        if self._get_next_state:
            outputs = rnn[0]
            states = [rnn[1], rnn[2]] if self._mode == "lstm" else [rnn[1]]
        else:
            outputs, states = rnn, []
        if layout == "NTC":
            outputs = sym.SwapAxis(outputs, dim1=0, dim2=1, name="%sntmajor" % self._prefix)
        if not merge_outputs:
            outputs = list(sym.SliceChannel(outputs, axis=axis, num_outputs=length,
                                            squeeze_axis=1))
        return outputs, states

    # ---------------------------------------------------- weight conversion
    def _slice_layout(self, input_size):
        """Yield (name, slice, shape) over the flat parameter blob —
        exactly the fused op's layout (ops/rnn.py:_unpack_params)."""
        g = self._num_gates()
        H = self._num_hidden
        d = len(self._directions)
        off = 0
        for layer in range(self._num_layers):
            in_sz = input_size if layer == 0 else H * d
            for di, dname in enumerate(self._directions):
                pre = "%s%s%d_" % (self._prefix, dname, layer)
                yield pre + "i2h_weight", slice(off, off + g * H * in_sz), (g * H, in_sz)
                off += g * H * in_sz
                yield pre + "h2h_weight", slice(off, off + g * H * H), (g * H, H)
                off += g * H * H
        for layer in range(self._num_layers):
            for dname in self._directions:
                pre = "%s%s%d_" % (self._prefix, dname, layer)
                yield pre + "i2h_bias", slice(off, off + g * H), (g * H,)
                off += g * H
                yield pre + "h2h_bias", slice(off, off + g * H), (g * H,)
                off += g * H

    def unpack_weights(self, args):
        """Fused blob → per-layer i2h/h2h arrays (reference:
        rnn_cell.py FusedRNNCell.unpack_weights)."""
        args = dict(args)
        blob = args.pop(self._prefix + "parameters")
        flat = blob.asnumpy() if hasattr(blob, "asnumpy") else np.asarray(blob)
        input_size = self._infer_input_size(flat)
        from ..ndarray import array

        for name, sl, shape in self._slice_layout(input_size):
            args[name] = array(flat[sl].reshape(shape))
        return args

    def pack_weights(self, args):
        args = dict(args)
        input_size = None
        g, H, d = self._num_gates(), self._num_hidden, len(self._directions)
        w0 = args["%s%s0_i2h_weight" % (self._prefix, self._directions[0])]
        input_size = (w0.shape if hasattr(w0, "shape") else np.shape(w0))[1]
        total = rnn_param_size(self._num_layers, input_size, H,
                               self._bidirectional, self._mode)
        flat = np.zeros((total,), dtype="float32")
        for name, sl, shape in self._slice_layout(input_size):
            v = args.pop(name)
            v = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
            flat[sl] = v.reshape(-1)
        from ..ndarray import array

        args[self._prefix + "parameters"] = array(flat)
        return args

    def _infer_input_size(self, flat):
        g, H, d = self._num_gates(), self._num_hidden, len(self._directions)
        L = self._num_layers
        total = len(flat)
        # solve rnn_param_size for input_size
        rest = total - L * d * 2 * g * H  # biases
        for layer in range(1, L):
            rest -= d * g * H * (H * d + H)
        # rest = d*g*H*(input+H)
        return rest // (d * g * H) - H

    def unfuse(self):
        """Equivalent stack of unfused cells (reference: rnn_cell.py unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden, activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden, activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p, forget_bias=0.0),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout, prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied layer by layer (reference: rnn_cell.py
    SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_shape(self):
        return [s for c in self._cells for s in c.state_shape]

    def begin_state(self, **kwargs):
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def unpack_weights(self, args):
        for c in self._cells:
            args = c.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for c in self._cells:
            args = c.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_shape)
            cell_states = states[pos : pos + n]
            pos += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def reset(self):
        super().reset()
        for c in getattr(self, "_cells", []):
            c.reset()


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence (reference: rnn_cell.py
    BidirectionalCell). Only supports unroll."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_shape(self):
        return self._l_cell.state_shape + self._r_cell.state_shape

    def begin_state(self, **kwargs):
        return self._l_cell.begin_state(**kwargs) + self._r_cell.begin_state(**kwargs)

    def unpack_weights(self, args):
        return self._r_cell.unpack_weights(self._l_cell.unpack_weights(args))

    def pack_weights(self, args):
        return self._r_cell.pack_weights(self._l_cell.pack_weights(args))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped — use unroll")

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=False):
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, sym.Symbol):
            inputs = list(sym.SliceChannel(inputs, axis=axis, num_outputs=length,
                                           squeeze_axis=1))
        if begin_state is None:
            begin_state = self.begin_state()
        n_l = len(self._l_cell.state_shape)
        l_outputs, l_states = self._l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l], layout=layout,
            merge_outputs=False)
        r_outputs, r_states = self._r_cell.unroll(
            length, inputs=list(reversed(inputs)), begin_state=begin_state[n_l:],
            layout=layout, merge_outputs=False)
        outputs = [
            sym.Concat(l, r, dim=1, num_args=2,
                       name="%st%d" % (self._output_prefix, i))
            for i, (l, r) in enumerate(zip(l_outputs, reversed(r_outputs)))
        ]
        if merge_outputs:
            outputs = [sym.expand_dims(o, axis=axis) for o in outputs]
            outputs = sym.Concat(*outputs, dim=axis, num_args=length)
        return outputs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py
    ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_shape(self):
        return self.base_cell.state_shape

    def begin_state(self, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class DropoutCell(BaseRNNCell):
    """Applies dropout to the input (reference: rnn_cell.py DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_shape(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(data=inputs, p=self._dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None
        if hasattr(self, "base_cell"):
            self.base_cell.reset()

    def __call__(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        mask = lambda p, like: sym.Dropout(data=sym.ones_like(like), p=p) if hasattr(sym, "ones_like") else None
        prev_output = self.prev_output if self.prev_output is not None else next_output * 0.0
        if self.zoneout_outputs > 0:
            m = sym.Dropout(data=next_output - next_output + 1.0, p=self.zoneout_outputs)
            output = sym.where(m, next_output, prev_output) if hasattr(sym, "where") else \
                m * 0.0 + next_output  # fallback: plain output
        else:
            output = next_output
        if self.zoneout_states > 0:
            zs = []
            for new_s, old_s in zip(next_states, states):
                m = sym.Dropout(data=new_s - new_s + 1.0, p=self.zoneout_states)
                zs.append(sym.where(m, new_s, old_s) if hasattr(sym, "where") else new_s)
            next_states = zs
        self.prev_output = output
        return output, next_states


class ResidualCell(ModifierCell):
    """Adds the input to the output (residual connection)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def unroll(self, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC", merge_outputs=False):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state,
            input_prefix=input_prefix, layout=layout, merge_outputs=False)
        self.base_cell._modified = True
        if isinstance(inputs, sym.Symbol):
            axis = layout.find("T")
            inputs = list(sym.SliceChannel(inputs, axis=axis, num_outputs=length,
                                           squeeze_axis=1))
        outputs = [o + i for o, i in zip(outputs, inputs)]
        if merge_outputs:
            axis = layout.find("T")
            outputs = [sym.expand_dims(o, axis=axis) for o in outputs]
            outputs = sym.Concat(*outputs, dim=axis, num_args=length)
        return outputs, states
