"""Bucketing sentence iterator (reference: python/mxnet/rnn/io.py:61
BucketSentenceIter). Pads each sentence to its bucket length; batches are
grouped per bucket so the BucketingModule compiles one executable per shape —
the executor-per-bucket economics the reference built on shared memory pools
(SURVEY.md §5.7) map to XLA's compile-cache-per-shape here."""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from ..io import DataBatch, DataDesc, DataIter
from ..ndarray import array

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """Iterate over sentences of varying length, bucketed + padded.

    ``sentences`` is a list of lists of int token ids. ``buckets`` is a sorted
    list of bucket lengths (auto-derived when None). ``invalid_label`` pads.
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT", label_shift=1, shuffle=True, seed=0):
        super().__init__(batch_size)
        if not buckets:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens) if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets = sorted(buckets)

        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[: len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        self.ndiscard = ndiscard

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.label_shift = label_shift
        self.shuffle = shuffle
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)
        self._rng = _pyrandom.Random(seed)

        self.provide_data = [DataDesc(data_name, self._shape(self.default_bucket_key), dtype, layout)]
        self.provide_label = [DataDesc(label_name, self._shape(self.default_bucket_key), dtype, layout)]
        self.reset()

    def _shape(self, seq_len):
        if self.major_axis == 0:
            return (self.batch_size, seq_len)
        return (seq_len, self.batch_size)

    def reset(self):
        self.curr_idx = 0
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in range(0, len(buck) - self.batch_size + 1, self.batch_size))
        if self.shuffle:
            self._rng.shuffle(self.idx)
            for buck in self.data:
                self._rng.shuffle(list(range(len(buck))))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        buck = self.data[i][j : j + self.batch_size]
        # next-token label, like the reference examples: label[t] = data[t+1]
        label = np.full_like(buck, self.invalid_label)
        label[:, : -self.label_shift] = buck[:, self.label_shift :]
        if self.major_axis == 1:
            buck = buck.T
            label = label.T
        seq_len = self.buckets[i]
        return DataBatch(
            [array(buck)], [array(label)], pad=0, bucket_key=seq_len,
            provide_data=[DataDesc(self.data_name, buck.shape, buck.dtype, "NT" if self.major_axis == 0 else "TN")],
            provide_label=[DataDesc(self.label_name, label.shape, label.dtype, "NT" if self.major_axis == 0 else "TN")],
        )
