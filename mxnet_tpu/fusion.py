"""Pattern-based subgraph fusion over the Symbol DAG.

Two generations of machinery live here, one engine:

**Conv+BN (the first migrated pattern, PR 2/round-5 perf work).** The
reference reached vendor-kernel conv+BN throughput via cuDNN
(/root/reference/src/operator/cudnn_convolution-inl.h with the CUDNN BN /
fused-add epilogues of batch_norm.cu); the TPU translation is a graph pass
that rewrites eligible subgraphs onto the Pallas kernel in
``ops/pallas_conv_bn.py``. Three rewrites compose along the pre-activation
ResNet chain (BN -> relu -> Conv -> [+res] -> BN ...; models/resnet.py):

- **prologue fold**: a BatchNorm whose (relu) output feeds only eligible
  convolutions never materializes — its per-channel ``scale``/``shift`` ride
  into each consumer kernel's VMEM prologue (saves one activation write +
  one read per edge).
- **stats reuse**: a BatchNorm whose input carries kernel-emitted
  ``(sum, sum_sq)`` skips its statistics pass entirely (saves one activation
  read) whether or not it folds.
- **residual defer**: a convolution whose only consumer is an elementwise
  add runs *at the add site* with the other operand streamed into its
  epilogue (saves the separate read-read-write add pass), and the sum's
  statistics feed the next block's BatchNorm.

The plan is structural (built once per program from the Symbol DAG); the
per-shape engage/fallback decision is made at trace time against the
committed on-chip WINS table (``ops/fused_conv_bn_table.py``), overridable
with ``MXNET_FUSED_CONV_BN=0|1|auto``. Every fallback path degrades to the
ordinary XLA lowering, including mid-chain (a Deferred input materializes
its normalized activation once, cached, shared by all fallback consumers).

Autodiff: only the Pallas kernel is a custom_vjp; the per-channel BN math
here (mean/var from sums, scale/shift, moving-stat updates) is plain traced
JAX, so gradients for gamma/beta flow through ``scale32``/``shift32`` into
the kernel's hand-written f32-accumulated prologue cotangents.

**The generic pattern engine (this round).** ``ops/fusion_patterns.py``
declares matchers + fused lowerings for matmul+bias+act, attention,
norm+residual and elementwise chains; ``plan()`` roots each match in the
directive map (interior nodes elide behind ``Lazy`` markers), and the
per-(pattern, shape, dtype, device-kind) engage decision comes from the
persistent measure-and-cache autotuner (``fusion_tune.py``) — TVM's
measured-schedule discipline replacing the committed WINS table, which
remains the conv+BN seed/fallback when tuning is disabled
(``MXNET_FUSION_TUNE_DIR`` unset). ``MXNET_FUSED_PATTERNS`` selects and
forces patterns (docs/ENV_VARS.md); every fallback path — gate declined,
tuner rejected, lowering unavailable — is the bit-identical unfused graph.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .ops.pallas_conv_bn import (_xla_conv, conv_block, conv_block_infer,
                                 plan_blocks, plan_bwd_blocks, strided_dims,
                                 supported)
from . import telemetry as _tm

__all__ = ["plan", "plan_sites", "execute", "resolve", "gate",
           "gate_explain", "bwd_mode", "conv_reject_reason",
           "bn_reject_reason", "infer_default", "quant_mode",
           "enabled_patterns", "gate_pattern_explain", "conv_schedule",
           "losers_note", "attention_trains_flash", "CONV_BN_KINDS"]

#: directive kinds owned by the conv+BN machinery — the executor masks these
#: (only) on inference executions where ``infer_default()`` declined, keeping
#: CPU eval numerics byte-identical to the unfused op-by-op lowering
CONV_BN_KINDS = frozenset({"conv", "bn", "relu_fold", "resadd"})


# --------------------------------------------------------------------- values
class Deferred:
    """A folded BN(+relu) output: ``relu(raw * scale + shift)``, not yet
    materialized. ``materialize()`` builds (and caches) the XLA elementwise
    form for consumers that fall back."""

    __slots__ = ("raw", "scale", "shift", "relu", "_mat")

    def __init__(self, raw, scale, shift, relu=False):
        self.raw, self.scale, self.shift, self.relu = raw, scale, shift, relu
        self._mat = None

    def with_relu(self):
        return Deferred(self.raw, self.scale, self.shift, relu=True)

    def materialize(self):
        if self._mat is None:
            out = _normalize(self.raw, self.scale, self.shift)
            if self.relu:
                out = jnp.maximum(out, 0)
            self._mat = out
        return self._mat


class WithStats:
    """A conv/add output plus the kernel's per-channel f32 (sum, sum_sq)."""

    __slots__ = ("c", "ssum", "ssq")

    def __init__(self, c, ssum, ssq):
        self.c, self.ssum, self.ssq = c, ssum, ssq


class PendingConv:
    """A conv deferred to its consuming residual add."""

    __slots__ = ("x", "w", "scale", "shift", "relu", "kernel", "stride",
                 "bwd", "bn")

    def __init__(self, x, w, scale, shift, relu, kernel, stride, bwd="xla",
                 bn=None):
        self.x, self.w = x, w
        self.scale, self.shift, self.relu = scale, shift, relu
        self.kernel, self.stride = kernel, stride
        self.bwd = bwd
        self.bn = bn

    def run(self, res):
        kind, mesh, _ = _mesh_kind()
        if kind == _MESH_DP:
            return _conv_block_sharded(
                mesh, self.x, self.w, self.scale, self.shift, res,
                self.kernel, self.stride, self.relu, self.bwd, self.bn)
        return conv_block(self.x, self.w, self.scale, self.shift, res,
                          self.kernel, self.stride, self.relu, True,
                          self.bwd, self.bn)


class Lazy:
    """A pattern-interior node's not-yet-computed output. Carries the node
    and its raw input values (possibly markers themselves); ``materialize()``
    runs the ordinary opdef — the bit-identical unfused semantics — and
    caches, so a marker consumed by both its pattern root (which fell back)
    and nothing else still computes at most once."""

    __slots__ = ("node", "ins", "_mat")

    def __init__(self, node, ins):
        self.node, self.ins = node, list(ins)
        self._mat = None

    def materialize(self):
        if self._mat is None:
            from .ops.registry import get_op

            vals = [resolve(v) for v in self.ins]
            outs, _ = get_op(self.node.op).apply(
                self.node.parsed_attrs(), vals, aux=[], is_train=False,
                rng=None)
            self._mat = outs[0]
        return self._mat


def resolve(v):
    """Any op that is not fusion-aware sees a plain tensor."""
    if isinstance(v, WithStats):
        return v.c
    if isinstance(v, (Deferred, Lazy)):
        return v.materialize()
    if isinstance(v, PendingConv):
        # defensive: plan() keeps graph-output convs out of the defer
        # rewrite, so a marker should never escape to a consumer that is
        # not the planned resadd — but if one does, its standalone value
        # (no residual) is exactly the conv output
        return v.run(None)[0]
    return v


# ------------------------------------------------------- normalize (custom_vjp)
@jax.custom_vjp
def _normalize(x, scale32, shift32):
    b = (1, -1) + (1,) * (x.ndim - 2)
    return x * scale32.astype(x.dtype).reshape(b) \
        + shift32.astype(x.dtype).reshape(b)


def _normalize_fwd(x, scale32, shift32):
    return _normalize(x, scale32, shift32), (x, scale32)


def _normalize_bwd(saved, dout):
    # explicit f32 accumulators for the per-channel reductions (plain
    # autodiff would reduce in the activation dtype — bf16 over B*H*W)
    x, scale32 = saved
    b = (1, -1) + (1,) * (x.ndim - 2)
    axes = (0,) + tuple(range(2, x.ndim))
    dx = dout * scale32.astype(dout.dtype).reshape(b)
    dout32 = dout.astype(jnp.float32)
    dscale = jnp.sum(dout32 * x.astype(jnp.float32), axis=axes)
    dshift = jnp.sum(dout32, axis=axes)
    return dx, dscale, dshift


_normalize.defvjp(_normalize_fwd, _normalize_bwd)


# ----------------------------------------------------------------------- plan
def _pair(v, fill):
    v = tuple(v or ())
    return v if len(v) == 2 else (fill, fill)


def conv_reject_reason(node):
    """The exact predicate that bars this Convolution from the Pallas path,
    or None when it is structurally eligible (shape gating still happens at
    trace time). The analysis subsystem (analysis/fusion_explain.py) reports
    these verbatim, so keep each reason a precise, single predicate."""
    if node.op != "Convolution":
        return "not a Convolution"
    if len(node.inputs) != 2:
        return "bias input present (no_bias=False): the kernel has no bias epilogue"
    a = node.parsed_attrs()
    kernel = tuple(a.get("kernel") or ())
    stride = _pair(a.get("stride"), 1)
    pad = _pair(a.get("pad"), 0)
    dilate = _pair(a.get("dilate"), 1)
    if a.get("num_group", 1) != 1:
        return "grouped convolution (num_group=%s != 1)" % a.get("num_group")
    if dilate != (1, 1):
        return "dilated convolution (dilate=%s)" % (dilate,)
    if kernel == (1, 1):
        if pad != (0, 0):
            return "1x1 kernel needs pad=(0, 0), got pad=%s" % (pad,)
        if stride not in ((1, 1), (2, 2)):
            return "1x1 kernel needs stride (1, 1) or (2, 2), got %s" % (stride,)
        return None
    if kernel == (3, 3):
        if pad != (1, 1):
            return "3x3 kernel needs pad=(1, 1), got pad=%s" % (pad,)
        if stride != (1, 1):
            return "3x3 kernel needs stride=(1, 1), got %s" % (stride,)
        return None
    return ("kernel %s has no Pallas variant (supported: 1x1 pad 0 stride "
            "1 or 2; 3x3 pad 1 stride 1)" % (kernel,))


def _conv_cfg(node):
    """(kernel, stride) if this Convolution can run on the Pallas path
    (structurally — shape gating happens at trace time), else None."""
    if conv_reject_reason(node) is not None:
        return None
    a = node.parsed_attrs()
    return tuple(a.get("kernel") or ()), _pair(a.get("stride"), 1)


def bn_reject_reason(node):
    """The exact predicate that bars this BatchNorm from the fusion plan,
    or None when eligible."""
    if node.op != "BatchNorm":
        return "not a BatchNorm"
    a = node.parsed_attrs()
    if a.get("use_global_stats"):
        return "use_global_stats=True: inference-style BN never runs the batch statistics pass the fusion reuses"
    if a.get("output_mean_var"):
        return "output_mean_var=True: the mean/var outputs must materialize, so the BN cannot stay folded"
    return None


def _bn_ok(node):
    return bn_reject_reason(node) is None


def enabled_patterns(infer=False):
    """Per-pattern mode map from ``MXNET_FUSED_PATTERNS``: name ->
    ``"auto"`` (engage per measured verdict), ``"1"`` (force the first
    candidate lowering), ``"0"`` (off), or a LOWERING NAME (force that
    specific candidate — ``attention=pallas_flash`` — where it exists for
    the site; prefix-matched, so a forced name also selects its schedule
    variants). Grammar: ``auto``/``all`` (every pattern in auto, the
    default), ``0``/``off``/``none``, or a comma list of names with
    optional forces (``attention,matmul_bias_act=1``) — listed patterns
    get their mode, unlisted ones are off. The conv+BN pattern is governed
    by its own ``MXNET_FUSED_CONV_BN[_BWD]`` knobs.

    ``infer=True`` is the serving/grad-less gate: when
    ``MXNET_FUSED_PATTERNS_INFER`` is set it overrides the training map on
    inference executions only (same grammar), so a serving fleet can pin
    its own pattern set — e.g. disable a pattern whose inference shapes
    were never tuned — without touching training behavior.

    The parse is memoized on the raw env string (the faultinject idiom):
    the per-site gate consults this map on every pattern execution during
    trace, so re-splitting the grammar there would be pure overhead.
    Callers get a fresh copy — ``plan()`` mutates its map."""
    from .ops.fusion_patterns import pattern_names

    names = pattern_names()
    env = os.environ.get("MXNET_FUSED_PATTERNS", "auto").strip().lower()
    if infer:
        env = os.environ.get("MXNET_FUSED_PATTERNS_INFER",
                             env).strip().lower() or env
    cached = _patterns_env_memo.get(env)
    if cached is not None:
        return dict(cached)
    modes = _parse_patterns_env(env, names)
    _patterns_env_memo[env] = modes
    return dict(modes)


def _parse_patterns_env(env, names):
    if env in ("", "auto", "all", "1"):
        return {n: "auto" for n in names}
    if env in ("0", "off", "none"):
        return {n: "0" for n in names}
    modes = {n: "0" for n in names}
    for item in env.split(","):
        item = item.strip()
        if not item:
            continue
        if item in ("auto", "all"):
            modes = {n: "auto" for n in names}
            continue
        name, _, val = item.partition("=")
        if name in modes:
            if val in ("0", "1"):
                modes[name] = val
            elif val in ("", "auto"):
                modes[name] = "auto"
            else:
                # a forced lowering NAME (e.g. pallas_flash). A value
                # matching no known lowering family warns once — a typo'd
                # value here used to read as "auto", and as a
                # never-matching name it would silently unfuse every site
                modes[name] = val
                if (val not in _warned_forced_vals
                        and not val.startswith(_LOWERING_FAMILIES)):
                    _warned_forced_vals.add(val)
                    import logging

                    logging.getLogger("mxnet_tpu").warning(
                        "MXNET_FUSED_PATTERNS treats %s=%r as a FORCED "
                        "lowering name, and it matches no known lowering "
                        "family %s: every site will run unfused (use "
                        "auto/0/1 for the mode grammar)",
                        name, val, list(_LOWERING_FAMILIES))
        else:
            global _warned_patterns_env
            if not _warned_patterns_env:
                _warned_patterns_env = True
                import logging

                logging.getLogger("mxnet_tpu").warning(
                    "MXNET_FUSED_PATTERNS names unknown pattern %r "
                    "(known: %s)", name, ", ".join(names))
    return modes


_warned_patterns_env = False
_warned_forced_vals = set()
_patterns_env_memo = {}
#: candidate-name families the patterns emit (forced-name validation)
_LOWERING_FAMILIES = ("pallas", "block_causal", "chunked_kv", "fused",
                      "onepass", "xla")


def plan_sites(directives):
    """Static per-pattern site inventory of one fusion plan:
    ``(pattern_sites, conv_bn_directive_count)``. Computed ONCE per bound
    program (``_GraphProgram.pattern_sites``) — consumers (serving cache,
    health probes, the graphlint --rewrite dump) read the cached inventory
    instead of re-walking the directive map."""
    sites, conv_bn = {}, 0
    for d in directives.values():
        if d["kind"] == "pattern":
            name = d["pat"].name
            sites[name] = sites.get(name, 0) + 1
        elif d["kind"] != "lazy":
            conv_bn += 1
    return sites, conv_bn


class _PlanCtx:
    """What pattern matchers may see of the graph: the consumer map, the
    program-output ids, and the directives built so far (``claimed``)."""

    __slots__ = ("consumers", "output_ids", "claimed")

    def __init__(self, consumers, output_ids, claimed):
        self.consumers, self.output_ids = consumers, output_ids
        self.claimed = claimed


def plan(topo, output_ids=()):
    """Build the fusion plan: id(node) -> directive dict. Structural only.

    Two passes: the conv+BN rewrites (unless ``MXNET_FUSED_CONV_BN=0``),
    then each enabled generic pattern (``enabled_patterns()``) in priority
    order over the still-unclaimed nodes — a matched root gets a
    ``pattern`` directive, its interior nodes ``lazy`` markers.

    ``output_ids`` are the ids of nodes whose outputs are PROGRAM outputs
    (executor passes them from the bound symbol). A graph-output node has an
    implicit extra consumer the ``consumers`` map cannot see: its value must
    materialize, so it is excluded from the prologue-fold rewrite (the fold
    would save nothing), from the residual-defer rewrite (a deferred
    conv's ``PendingConv`` marker would otherwise escape ``interpret()`` as
    a program output and fail at jit trace time under
    ``MXNET_FUSED_CONV_BN=1``), and from every pattern interior."""
    output_ids = frozenset(output_ids)
    consumers = {}
    for node in topo:
        for inp, oi in node.inputs:
            consumers.setdefault(id(inp), []).append((node, oi))
    order = {id(n): i for i, n in enumerate(topo)}

    directives = {}
    if os.environ.get("MXNET_FUSED_CONV_BN", "auto") != "0":
        _plan_conv_bn(topo, output_ids, consumers, order, directives)

    # a pattern is PLANNED when either the training or the inference map
    # enables it (the per-execution gate re-reads the right map); the plan
    # is shared by both execution modes of a program
    modes = enabled_patterns()
    for name, mode in enabled_patterns(infer=True).items():
        if modes.get(name, "0") == "0" and mode != "0":
            modes[name] = mode
    if any(v != "0" for v in modes.values()):
        from .ops.fusion_patterns import get_patterns

        ctx = _PlanCtx(consumers, output_ids, directives)
        for pat in get_patterns():
            if modes.get(pat.name, "0") == "0":
                continue
            for node in topo:
                if node.is_variable or id(node) in directives:
                    continue
                m = pat.match(node, ctx)
                if m is None:
                    continue
                directives[id(node)] = {"kind": "pattern", "pat": pat,
                                        "meta": m.meta}
                for n in m.interior:
                    directives[id(n)] = {"kind": "lazy"}
    return directives


def _plan_conv_bn(topo, output_ids, consumers, order, directives):
    """The conv+BN rewrite pass (prologue fold, stats reuse, residual
    defer) — fills ``directives`` in place."""
    conv_nodes = {}
    for node in topo:
        if node.is_variable:
            continue
        cfg = _conv_cfg(node)
        if cfg is not None:
            directives[id(node)] = {"kind": "conv", "kernel": cfg[0],
                                    "stride": cfg[1], "defer": False}
            conv_nodes[id(node)] = node
        elif _bn_ok(node):
            directives[id(node)] = {"kind": "bn", "fold": False}

    def _is_fusable_conv_data_edge(cons_node, producer):
        d = directives.get(id(cons_node))
        return (d is not None and d["kind"] == "conv"
                and cons_node.inputs[0][0] is producer)

    # prologue folds: BN (-> relu) whose every consumer is a fusable conv's
    # data input
    for node in topo:
        d = directives.get(id(node))
        if not d or d["kind"] != "bn":
            continue
        cons = consumers.get(id(node), [])
        if not cons:
            continue
        relu_node = None
        targets = [c for c, oi in cons if oi == 0]
        if len(cons) == 1 and len(targets) == 1:
            c0 = targets[0]
            if (c0.op == "Activation"
                    and c0.parsed_attrs().get("act_type") == "relu"):
                relu_node = c0
                targets = [c for c, oi in consumers.get(id(c0), []) if oi == 0]
                if len(targets) != len(consumers.get(id(c0), [])):
                    continue
        src = relu_node if relu_node is not None else node
        if id(node) in output_ids or id(src) in output_ids:
            continue  # the BN (or its relu) value materializes regardless
        if targets and all(_is_fusable_conv_data_edge(c, src)
                           for c in targets):
            d["fold"] = True
            if relu_node is not None:
                directives[id(relu_node)] = {"kind": "relu_fold"}

    # residual defers: elemwise_add with an operand whose only consumer is
    # the add and whose producer is a fusable conv
    for node in topo:
        if node.op != "elemwise_add" or len(node.inputs) != 2:
            continue
        best = None
        for slot, (inp, oi) in enumerate(node.inputs):
            if oi != 0 or id(inp) not in conv_nodes:
                continue
            if id(inp) in output_ids:
                continue  # program output: the conv must materialize
            if len(consumers.get(id(inp), [])) != 1:
                continue
            if best is None or order[id(inp)] > order[id(best[1])]:
                best = (slot, inp)
        if best is not None:
            slot, conv = best
            directives[id(conv)]["defer"] = True
            directives[id(node)] = {"kind": "resadd", "pending_slot": slot}
    return directives


# ----------------------------------------------------------------------- gate
def _table_device_matches():
    """The WINS table is an on-chip measurement: it only applies on the
    device generation it was taken on (interpret-mode Pallas on CPU would be
    orders of magnitude slower than the XLA path the table says it beats)."""
    from .ops.fused_conv_bn_table import DEVICE

    if DEVICE is None:
        return False
    import jax

    try:
        return jax.devices()[0].device_kind == DEVICE
    except Exception:
        return False


def _conv_bn_key(kernel, stride, x_shape, w_shape, dtype, res):
    import numpy as np

    return "conv_bn|k%ds%d%s|%s%s;%s" % (
        kernel[0], stride[0], "pr" if res else "p",
        np.dtype(dtype).name, tuple(x_shape), tuple(w_shape))


def _conv_bn_measure(kernel, stride, x_shape, w_shape, dtype, res):
    """The PR 2 fwd+bwd autotune contract for one conv+BN site, as a
    fusion_tune measurement: unfused (XLA conv + stats re-read) vs the
    Pallas ``conv_block`` under each tileable backward policy. The winning
    candidate name (``pallas:<policy>``) carries the backward mode
    ``bwd_mode`` rides into ``conv_block(bwd=...)``."""
    import functools

    import numpy as np

    from .fusion_tune import measure_candidates
    from .ops.pallas_conv_bn import _stats_of

    rs = np.random.RandomState(0)
    dt = jnp.dtype(dtype)
    itemsize = dt.itemsize
    x = jnp.asarray(rs.randn(*x_shape), dt)
    w = jnp.asarray(rs.randn(*w_shape) * 0.1, dt)
    K = x_shape[1]
    scale = jnp.asarray(rs.uniform(0.5, 1.5, (K,)), jnp.float32)
    shift = jnp.asarray(rs.uniform(-0.2, 0.2, (K,)), jnp.float32)
    args = [x, w, scale, shift]
    if res:
        Ho, Wo = strided_dims(x_shape[2], x_shape[3], stride)
        args.append(jnp.asarray(
            rs.randn(x_shape[0], w_shape[0], Ho, Wo) * 0.1, dt))

    def baseline(x, w, scale, shift, r=None):
        c = _xla_conv(x, w, scale, shift, r, kernel, stride, True)
        s, q = _stats_of(c)
        return (c, s, q)

    def fused(x, w, scale, shift, r=None, bwd="xla", bn=None):
        return conv_block(x, w, scale, shift, r, kernel, stride, True,
                          True, bwd, bn)

    from . import fusion_tune as _tune
    from .ops.pallas_conv_bn import _conv_geometry, bn_candidates

    geo = _conv_geometry(tuple(x_shape), tuple(w_shape), stride, itemsize)
    budget = _tune.schedule_budget()
    cands = []
    for policy in ("xla", "recompute", "stash"):
        if policy != "xla":
            if (policy == "stash" and plan_blocks(
                    x_shape, w_shape, stride, itemsize=itemsize,
                    prologue=True, res=res, emit_xn=True) is None):
                continue
            if plan_bwd_blocks(x_shape, w_shape, stride, itemsize=itemsize,
                               prologue=True, res=res,
                               stash=(policy == "stash")) is None:
                continue
        cands.append(("pallas:" + policy,
                      functools.partial(fused, bwd=policy)))
        if geo is not None and budget:
            # the forward stripe's schedule axis (choose_blocks seeds the
            # bare-name default; the variants carry their measured stripe)
            B_, K_, N_, HW_, taps_ = geo
            bns = bn_candidates(B_, K_, N_, HW_, itemsize, taps=taps_,
                                prologue=True, res=res,
                                emit_xn=(policy == "stash"))
            cands.extend(
                (_tune.sched_name("pallas:" + policy, bn=bn),
                 functools.partial(fused, bwd=policy, bn=bn))
                for bn in bns[1:1 + budget])
    return measure_candidates(baseline, cands, tuple(args), train=True)


def _conv_bn_verdict(kernel, stride, x_shape, w_shape, dtype, res):
    """The measured verdict for this conv+BN site — cache hit, measure on
    miss (tuning enabled), else None (committed WINS table decides)."""
    from . import fusion_tune as _tune

    if _tune.cache_dir() is None:
        return None
    key = _conv_bn_key(kernel, stride, x_shape, w_shape, dtype, res)
    return _tune.verdict(key, lambda: _conv_bn_measure(
        kernel, stride, x_shape, w_shape, dtype, res))


def _conv_bn_peek(kernel, stride, x_shape, w_shape, dtype, res):
    """Cache-only read of the conv+BN verdict (never measures) — the
    ``bwd_mode`` consult, which must not tune from inside a policy query."""
    from . import fusion_tune as _tune

    return _tune.peek(_conv_bn_key(kernel, stride, x_shape, w_shape, dtype,
                                   res))


def conv_schedule(kernel, stride, x_shape, w_shape, dtype, res):
    """The tuned forward channel-stripe override (``@bn=…``) for an
    ENGAGED conv+BN site, or None (planner default / no searched winner /
    v1 binary-verdict record). Cache-only read."""
    rec = _conv_bn_peek(kernel, stride, x_shape, w_shape, dtype, res)
    if not rec or not rec.get("engage"):
        return None
    sched = rec.get("schedule")
    if isinstance(sched, dict) and isinstance(sched.get("bn"), int):
        return sched["bn"]
    return None


def gate_explain(kernel, stride, x_shape, w_shape, dtype, prologue,
                 res=False, train=True):
    """The per-shape engage decision WITH the predicate that made it:
    ``(engaged, reason)``. Same predicate order as the reference planner's
    gate; ``gate`` is this plus telemetry counting. Keep each reason a
    single precise predicate — telemetry spans and fusion_explain (GL301)
    report them verbatim.

    ``train=False`` is the inference predicate (grad-less bind): the same
    shape/VMEM and WINS checks apply, but no backward budget exists — the
    stash/bwd-policy machinery (``bwd_mode``) is never consulted, so a
    shape only needs the FORWARD win to engage."""
    env = os.environ.get("MXNET_FUSED_CONV_BN", "auto")
    if env == "0":
        return False, "MXNET_FUSED_CONV_BN=0 (fusion disabled)"
    if not supported(x_shape, w_shape, stride,
                     itemsize=jnp.dtype(dtype).itemsize,
                     prologue=prologue, res=res):
        return False, ("shape %sx%s does not tile within the VMEM budget "
                       "(supported() declined)" % (x_shape, w_shape))
    if env == "1":
        return True, "forced (MXNET_FUSED_CONV_BN=1)"
    if not prologue:
        return False, ("bare conv (no folded BN prologue): no measured "
                       "WINS contract, never engages in auto mode")
    rec = _conv_bn_verdict(kernel, stride, x_shape, w_shape, dtype, res)
    if rec is not None:
        want = "engage" if train else "engage_fwd"
        if rec.get(want):
            times = _rec_best_times(rec)
            return True, ("measured win (tuned: fused %.0fµs vs xla "
                          "%.0fµs fwd+bwd%s)"
                          % (times + (losers_note(rec,
                                                  rec.get("lowering")),))
                          if times else "measured win (tuned)")
        return False, tuned_reject_note(rec)
    # seed/fallback when tuning is disabled: the committed on-chip table
    if not _table_device_matches():
        return False, ("WINS table absent or measured on a different "
                       "device generation")
    from .ops.fused_conv_bn_table import WINS

    if bool(WINS.get(_wins_key(kernel, stride, x_shape, w_shape, res),
                     False)):
        return True, ("WINS-table win for this shape"
                      if train else
                      "WINS-table forward win for this shape (inference: "
                      "no backward budget to clear)")
    return False, "no WINS-table win for this shape"


def gate(kernel, stride, x_shape, w_shape, dtype, prologue, res=False,
         train=True):
    """Per-shape engage decision: env override, else the committed on-chip
    WINS table (device-matched, per measured VARIANT — 'p' prologue-only,
    'pr' prologue+residual; bare convs have no measured contract and never
    engage in auto mode), else off. Untileable calls never engage.
    ``train=False`` counts into the ``fusion.infer_*`` telemetry family
    instead of ``fusion.fwd_*``."""
    engaged, _ = gate_explain(kernel, stride, x_shape, w_shape, dtype,
                              prologue, res=res, train=train)
    if _tm.enabled():
        if train:
            _tm.counter("fusion.fwd_engaged" if engaged
                        else "fusion.fwd_fallback").inc()
        else:
            _tm.counter("fusion.infer_engaged" if engaged
                        else "fusion.infer_fallback").inc()
    return engaged


def infer_default():
    """Whether the fusion plan is ACTIVE on inference (grad-less /
    ``is_train=False``) executions of a program. Distinct from the
    per-shape ``gate`` decision: an active plan applies the structural
    rewrites (BN prologue fold, moving-stat constant fold, quantized
    weights) with the per-shape Pallas engage still decided by
    ``gate(train=False)``; an inactive plan leaves inference on the plain
    op-by-op lowering, byte-identical to the pre-serving behavior.

    Active when fusion is forced (``MXNET_FUSED_CONV_BN=1``), when the
    committed WINS table matches this device generation (on-chip serving),
    or when a quantized inference variant is requested
    (``MXNET_SERVE_QUANT`` — quantization is applied by the fused execute
    path, so it needs the plan live even where Pallas declines)."""
    env = os.environ.get("MXNET_FUSED_CONV_BN", "auto")
    if env == "0":
        return False
    if env == "1":
        return True
    if quant_mode() != "off":
        return True
    return _table_device_matches()


def _wins_key(kernel, stride, x_shape, w_shape, res):
    """The per-shape WINS-table key. The spatial term uses the kernel's own
    post-stride arithmetic (ceil for odd dims) so the key always matches
    what tools/fused_stats_bench.py measured and emitted."""
    Ho, Wo = strided_dims(x_shape[2], x_shape[3], stride)
    return (kernel[0], x_shape[1], w_shape[0], Ho * Wo, stride[0],
            "pr" if res else "p")


_warned_bwd_env = False


def bwd_mode(kernel, stride, x_shape, w_shape, dtype, prologue, res=False):
    """The stash-vs-recompute policy for the fused backward, decided per
    shape (see ``_bwd_mode_impl``); counts ``fusion.bwd_engaged`` /
    ``fusion.bwd_xla`` into the telemetry registry when enabled."""
    mode = _bwd_mode_impl(kernel, stride, x_shape, w_shape, dtype, prologue,
                          res=res)
    if _tm.enabled():
        _tm.counter("fusion.bwd_xla" if mode == "xla"
                    else "fusion.bwd_engaged").inc()
    return mode


def _bwd_mode_impl(kernel, stride, x_shape, w_shape, dtype, prologue,
                   res=False):
    """The stash-vs-recompute policy for the fused backward, decided per
    shape like ``choose_blocks`` (docs/PERF.md §6b):

    - ``MXNET_FUSED_CONV_BN_BWD=0|xla`` pins the jax.vjp-of-XLA backward;
      ``recompute``/``stash`` force a policy (measurement) where the shape
      tiles;
    - ``auto`` (default) consults the committed WINS table's backward
      entries — key ``(..., variant + ":bwd")``, value the measured winning
      policy string — device-matched like the forward gate.

    Only meaningful when the forward engages (``gate`` returned True for
    the same call); the returned mode rides into ``conv_block(bwd=...)``.
    """
    env = os.environ.get("MXNET_FUSED_CONV_BN_BWD", "auto")
    if env in ("0", "xla"):
        return "xla"
    if env == "1":
        env = "recompute"  # mirror MXNET_FUSED_CONV_BN=1 force semantics
    elif env not in ("auto", "recompute", "stash"):
        global _warned_bwd_env
        if not _warned_bwd_env:
            _warned_bwd_env = True
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "MXNET_FUSED_CONV_BN_BWD=%r not recognized "
                "(0|xla|1|recompute|stash|auto); backward stays on the XLA "
                "lowering", env)
        return "xla"
    itemsize = jnp.dtype(dtype).itemsize

    def _tiles(policy):
        if policy == "stash" and plan_blocks(
                x_shape, w_shape, stride, itemsize=itemsize,
                prologue=prologue, res=res, emit_xn=True) is None:
            return False  # forward cannot afford the xn output stream
        return plan_bwd_blocks(x_shape, w_shape, stride, itemsize=itemsize,
                               prologue=prologue, res=res,
                               stash=(policy == "stash")) is not None

    if env in ("recompute", "stash"):
        return env if _tiles(env) else "xla"
    if not prologue:
        return "xla"
    # measured verdict first (the forward gate already tuned this site —
    # cache-only read here, a policy query must never trigger a measurement)
    rec = _conv_bn_peek(kernel, stride, x_shape, w_shape, dtype, res)
    if rec is not None and rec.get("engage"):
        low = rec.get("lowering") or ""
        # "pallas:<policy>[@bn=…]" — the @-suffix is the forward stripe
        # schedule (conv_schedule reads it), not part of the policy
        policy = low.partition(":")[2].partition("@")[0]
        if policy in ("recompute", "stash") and _tiles(policy):
            return policy
        return "xla"
    if not _table_device_matches():
        return "xla"
    from .ops.fused_conv_bn_table import WINS

    k, K, N, hw, s, variant = _wins_key(kernel, stride, x_shape, w_shape,
                                        res)
    policy = WINS.get((k, K, N, hw, s, variant + ":bwd"))
    if policy in ("recompute", "stash") and _tiles(policy):
        return policy
    return "xla"


# ----------------------------------------------------- generic pattern gate
def _tune_key(pat, meta, args):
    from .ops.fusion_patterns import sig_of

    variant = pat.key_variant(meta)
    return "%s|%s|%s" % (pat.name, variant, sig_of(args))


def _rec_best_times(rec):
    """(fused_us, baseline_us) fwd+bwd totals from a tune record — the
    engaged lowering's when one won, else the best measured candidate's —
    for the explain strings GL302/GL303 quote. None when nothing timed."""
    base = rec.get("base_fwd_us")
    if base is None:
        return None
    base += rec.get("base_bwd_us") or 0.0
    if rec.get("fused_fwd_us") is not None:
        return (rec["fused_fwd_us"] + (rec.get("fused_bwd_us") or 0.0), base)
    best = None
    for row in (rec.get("measured") or {}).values():
        if row.get("fwd_us") is None:
            continue
        t = row["fwd_us"] + (row.get("bwd_us") or 0.0)
        best = t if best is None or t < best else best
    return None if best is None else (best, base)


def losers_note(rec, winner):
    """The measured-losers clause of a schedule-search win: up to three
    runner-up candidates with their fwd(+bwd) totals, fastest first —
    ``gate_explain``/``gate_pattern_explain`` reasons quote it so the
    schedule decision is auditable without opening the cache file."""
    rows = []
    for name, row in (rec.get("measured") or {}).items():
        if name == winner or row.get("fwd_us") is None:
            continue
        if "rejected" in row or "error" in row:
            continue  # failed parity / failed to run: not beaten on TIME
        rows.append((row["fwd_us"] + (row.get("bwd_us") or 0.0), name))
    if not rows:
        return ""
    rows.sort()
    note = ", ".join("%s %.0fµs" % (n, t) for t, n in rows[:3])
    extra = "" if len(rows) <= 3 else " +%d more" % (len(rows) - 3)
    return "; beat %s%s" % (note, extra)


def tuned_reject_note(rec):
    """The measured-timings clause for a tuned-and-rejected site (feeds the
    GL302 explainer and ``gate_pattern_explain`` reasons)."""
    if "error" in rec:
        return "tuned and failed to measure (%s)" % rec["error"]
    times = _rec_best_times(rec)
    if times is None:
        return "tuned and rejected (no candidate lowering could be timed)"
    return ("tuned and rejected (best fused %.0fµs vs baseline %.0fµs "
            "fwd+bwd)" % times)


def gate_pattern_explain(pat, meta, args, train=True):
    """The per-site engage decision for a generic pattern WITH its
    predicate: ``(engaged, (lowering_name, fn) | None, reason)``.

    Predicate order: env mode (``MXNET_FUSED_PATTERNS``) → inference
    eligibility → mesh (patterns engage single-device only; SPMD traces
    keep the op's own dispatch, e.g. ring attention) → candidate lowerings
    exist for these shapes → forced, else the measure-and-cache verdict
    (``fusion_tune``): cache hit engages/rejects with the measured µs;
    a miss MEASURES when tuning is enabled, else stays unfused."""
    from . import fusion_tune as _tune

    mode = enabled_patterns(infer=not train).get(pat.name, "0")
    if mode == "0":
        return False, None, ("pattern disabled (MXNET_FUSED_PATTERNS%s)"
                             % ("" if train else "[_INFER]"))
    if not train and not pat.inference:
        return False, None, "pattern does not engage on inference executions"
    if _mesh_kind()[0] != _MESH_NONE:
        return False, None, ("multi-device mesh: generic patterns engage "
                             "single-device only (the op's own SPMD "
                             "dispatch applies)")
    baseline, cands = pat.build(meta, args)
    if not cands:
        return False, None, ("no fused lowering for this site (shape does "
                             "not tile / variant unsupported)")
    if mode == "1":
        return True, cands[0], "forced (MXNET_FUSED_PATTERNS)"
    if mode != "auto":
        # a forced lowering NAME (prefix-matched so a bare family name
        # also selects its schedule variants): engage where it exists
        match = next((c for c in cands if c[0] == mode),
                     next((c for c in cands if c[0].startswith(mode)),
                          None))
        if match is not None:
            return True, match, ("forced (MXNET_FUSED_PATTERNS %s=%s)"
                                 % (pat.name, mode))
        return False, None, ("forced lowering %r has no candidate at "
                             "this site" % mode)
    if not getattr(pat, "tunable", True):
        return False, None, ("no lowering distinct from the baseline to "
                             "measure (engage via MXNET_FUSED_PATTERNS="
                             "%s=1)" % pat.name)
    key = _tune_key(pat, meta, args)

    def _measure():
        # synthetic concrete inputs: the real args are tracers mid-trace.
        # tuner_build() keeps force-gated interpret candidates (an
        # inference-map pin) out of the measured set off-TPU.
        from .ops.fusion_patterns import tuner_build

        sargs = _tune.synth_like(args)
        with tuner_build():
            sbase, scands = pat.build(meta, sargs)
        return _tune.measure_candidates(sbase, scands, sargs, train=True)

    rec = _tune.verdict(key, _measure)
    if rec is None:
        return False, None, ("no measured verdict for this site (tuning "
                             "disabled: set MXNET_FUSION_TUNE_DIR)")
    want = "engage" if train else "engage_fwd"
    low = rec.get("lowering") if train else (rec.get("lowering_fwd")
                                             or rec.get("lowering"))
    if rec.get(want) and low:
        fn = dict(cands).get(low)
        if fn is None:
            return False, None, ("cached lowering %r is unavailable for "
                                 "this site" % low)
        times = _rec_best_times(rec)
        reason = "measured win (%s)" % low if times is None else (
            "measured win (%s: fused %.0fµs vs baseline %.0fµs fwd+bwd%s)"
            % ((low,) + times + (losers_note(rec, low),)))
        return True, (low, fn), reason
    return False, None, tuned_reject_note(rec)


def attention_trains_flash(q_shape, k_shape, dtype, causal, scale=-1.0):
    """Whether TRAINING through an attention site with these shapes will
    statically engage the flash (``pallas_flash``) lowering — whose
    ``custom_vjp`` online-softmax recompute backward never stashes the
    (B, H, T, S) probability tensor. Decidable without tracing: the
    pattern mode force-names a flash lowering, or the tune cache records
    an engaged ``pallas_flash`` winner for this exact site. The GL5xx
    memory planner uses it to elide the score-stash charge."""
    try:
        from .ops import pallas_attention as pa

        if not pa.supported(tuple(q_shape), tuple(k_shape),
                            causal=bool(causal)):
            return False
        mode = enabled_patterns().get("attention", "0")
        if mode in ("0", "1"):
            return False  # "1" engages the FIRST candidate (XLA family)
        if mode != "auto":
            return mode.startswith("pallas_flash")
        from . import fusion_tune as _tune
        from .ops.fusion_patterns import get_patterns

        class _Arg:  # shape/dtype carrier for the tune-key signature
            def __init__(self, shape, dtype):
                self.shape, self.dtype = tuple(shape), dtype

        pat = next(p for p in get_patterns() if p.name == "attention")
        meta = {"causal": bool(causal), "scale": float(scale)}
        args = (_Arg(q_shape, dtype), _Arg(k_shape, dtype),
                _Arg(k_shape, dtype))
        rec = _tune.peek(_tune_key(pat, meta, args))
        return bool(rec and rec.get("engage")
                    and str(rec.get("lowering") or "").startswith(
                        "pallas_flash"))
    except Exception:  # a planner refinement must never sink an analysis
        return False


def _exec_pattern(directive, node, ins, is_train):
    """Run one pattern-rooted node: engage the gated lowering, or fall back
    to the bit-identical unfused root op over resolved inputs."""
    pat, meta = directive["pat"], directive["meta"]
    engaged, chosen, reason = False, None, None
    try:
        args = pat.externals(meta, ins, resolve)
    except Exception:  # matcher/exec mismatch: unfused fallback
        args, reason = None, "externals recovery failed (marker mismatch)"
    if args is not None:
        engaged, chosen, reason = gate_pattern_explain(
            pat, meta, args, train=is_train)
    if _tm.enabled():
        _tm.counter("fusion.pattern_engaged.%s" % pat.name if engaged
                    else "fusion.pattern_fallback.%s" % pat.name).inc()
    if _tm.tracing():
        _tm.event("fusion.pattern", op=node.name, pattern=pat.name,
                  engaged=engaged, reason=reason,
                  **({"lowering": chosen[0]} if chosen else {}))
    if engaged:
        return (chosen[1](*args),), ()
    from .ops.registry import get_op

    rins = [resolve(v) for v in ins]
    outs, aux_out = get_op(node.op).apply(
        node.parsed_attrs(), rins, aux=[], is_train=is_train, rng=None)
    return tuple(outs), tuple(aux_out)


# -------------------------------------------------------------------- execute
def execute(directive, node, ins, aux, is_train):
    """Run one planned node during interpret(). ``ins`` are the raw values
    (possibly fusion markers); returns (outs_tuple_or_marker, new_aux)."""
    kind = directive["kind"]
    if kind == "bn":
        return _exec_bn(directive, node, ins, aux, is_train)
    if kind == "relu_fold":
        v = ins[0]
        if isinstance(v, Deferred):
            return (v.with_relu(),), ()
        return (jnp.maximum(resolve(v), 0),), ()
    if kind == "conv":
        if not is_train:
            return (_exec_conv_infer(directive, node, ins),), ()
        return (_exec_conv(directive, node, ins),), ()
    if kind == "resadd":
        return (_exec_resadd(directive, ins),), ()
    if kind == "lazy":
        return (Lazy(node, ins),), ()
    if kind == "pattern":
        return _exec_pattern(directive, node, ins, is_train)
    raise AssertionError(kind)


def _exec_bn(directive, node, ins, aux, is_train=True):
    data_v, gamma, beta = ins
    moving_mean, moving_var = aux
    a = node.parsed_attrs()
    eps, momentum = float(a["eps"]), float(a["momentum"])
    fix_gamma = bool(a["fix_gamma"])

    if not is_train:
        # inference: normalize with the MOVING stats — per-channel scale and
        # shift are constants, so the fold costs nothing even mid-chain
        x = data_v.c if isinstance(data_v, WithStats) else resolve(data_v)
        istd = jax.lax.rsqrt(moving_var.astype(jnp.float32) + eps)
        scale32 = istd if fix_gamma else gamma.astype(jnp.float32) * istd
        shift32 = beta.astype(jnp.float32) \
            - moving_mean.astype(jnp.float32) * scale32
        if directive["fold"]:
            out = Deferred(x, scale32, shift32, relu=False)
        else:
            out = _normalize(x, scale32, shift32)
        return (out,), (moving_mean, moving_var)

    if isinstance(data_v, WithStats):
        x, ssum, ssq = data_v.c, data_v.ssum, data_v.ssq
    else:
        x = resolve(data_v)
        x32 = x.astype(jnp.float32)
        axes = (0,) + tuple(range(2, x.ndim))
        ssum = jnp.sum(x32, axis=axes)
        ssq = jnp.sum(x32 * x32, axis=axes)
    cnt = x.shape[0]
    for dim in x.shape[2:]:
        cnt *= dim
    mean = ssum / cnt
    var = ssq / cnt - mean * mean
    istd = jax.lax.rsqrt(var + eps)
    g32 = istd if fix_gamma else gamma.astype(jnp.float32) * istd
    scale32 = g32
    shift32 = beta.astype(jnp.float32) - mean * scale32

    sg = jax.lax.stop_gradient
    new_mean = moving_mean * momentum + sg(mean).astype(moving_mean.dtype) * (1 - momentum)
    new_var = moving_var * momentum + sg(var).astype(moving_var.dtype) * (1 - momentum)

    if directive["fold"]:
        out = Deferred(x, scale32, shift32, relu=False)
    else:
        out = _normalize(x, scale32, shift32)
    return (out,), (new_mean, new_var)


_MESH_NONE, _MESH_DP, _MESH_OTHER = 0, 1, 2


def _mesh_kind():
    """Tri-state: (_MESH_NONE, None, 0) outside any SPMD trace or on a
    1-device mesh (run the kernel directly); (_MESH_DP, mesh, dp) on a
    pure data-parallel mesh over a 'data' axis (run per-shard under
    shard_map with psum'd statistics); (_MESH_OTHER, None, 0) on any other
    multi-device mesh — tensor/seq-sharded, or a dp axis not named 'data' —
    where a raw pallas_call would make GSPMD gather its operands: those
    take the XLA fallback unconditionally."""
    from .parallel.mesh import current_trace_mesh

    mesh = current_trace_mesh()
    if mesh is None or mesh.size <= 1:
        return _MESH_NONE, None, 0
    dp = mesh.shape.get("data", 0) if "data" in mesh.axis_names else 0
    if dp == mesh.size:
        return _MESH_DP, mesh, dp
    return _MESH_OTHER, None, 0


def _conv_block_sharded(mesh, x, w, scale, shift, res, kernel, stride, relu,
                        bwd="xla", bn=None):
    """Run the kernel per data-shard (pallas_call has no SPMD partitioning
    rule, so GSPMD would gather its operands); the per-shard statistics
    psum over 'data' so the downstream BN sees GLOBAL-batch moments —
    identical semantics to the unfused dp path, where XLA turns the stats
    reduction over a sharded batch into the same collective."""
    import jax
    from jax.sharding import PartitionSpec as P

    args = [x, w]
    specs = [P("data", *([None] * (x.ndim - 1))), P(*([None] * w.ndim))]
    has_p, has_r = scale is not None, res is not None
    if has_p:
        args += [scale, shift]
        specs += [P(None), P(None)]
    if has_r:
        args.append(res)
        specs.append(P("data", *([None] * (res.ndim - 1))))

    def local(*a):
        it = iter(a)
        x_, w_ = next(it), next(it)
        sc = next(it) if has_p else None
        sh = next(it) if has_p else None
        r_ = next(it) if has_r else None
        c, s, q = conv_block(x_, w_, sc, sh, r_, kernel, stride, relu,
                             True, bwd, bn)
        return (c, jax.lax.psum(s, "data"), jax.lax.psum(q, "data"))

    from .parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        local, mesh=mesh, in_specs=tuple(specs),
        out_specs=(P("data", *([None] * (x.ndim - 1))), P(None), P(None)))
    return fn(*args)


def _note_conv(node, x_shape, engaged, reason, bwd=None):
    """Trace-time telemetry: one event per planned conv recording the
    per-shape engage-or-fallback decision with its predicate. Fires during
    jit tracing (once per compile, not per step) — the observable record of
    whether the Pallas path actually ran in this program."""
    if not _tm.tracing():
        return
    _tm.event("fusion.conv", op=node.name, shape=tuple(x_shape),
              engaged=engaged, reason=reason,
              **({} if bwd is None else {"bwd": bwd}))


def _exec_conv(directive, node, ins):
    v, w = ins
    kernel, stride = directive["kernel"], directive["stride"]
    if isinstance(v, Deferred):
        x, scale, shift, relu = v.raw, v.scale, v.shift, v.relu
    else:
        x, scale, shift, relu = resolve(v), None, None, False
    kind, mesh, dp = _mesh_kind()
    if kind == _MESH_DP:
        local_shape = (x.shape[0] // dp,) + x.shape[1:]
        if (x.shape[0] % dp == 0
                and gate(kernel, stride, local_shape, w.shape, x.dtype,
                         scale is not None, res=directive["defer"])):
            bwd = bwd_mode(kernel, stride, local_shape, w.shape, x.dtype,
                           scale is not None, res=directive["defer"])
            bn = conv_schedule(kernel, stride, local_shape, w.shape,
                               x.dtype, directive["defer"])
            _note_conv(node, local_shape, True, "engaged (dp mesh)", bwd)
            if directive["defer"]:
                return PendingConv(x, w, scale, shift, relu, kernel, stride,
                                   bwd, bn)
            c, s, q = _conv_block_sharded(mesh, x, w, scale, shift, None,
                                          kernel, stride, relu, bwd, bn)
            return WithStats(c, s, q)
    elif kind == _MESH_NONE and gate(kernel, stride, x.shape, w.shape,
                                     x.dtype, scale is not None,
                                     res=directive["defer"]):
        bwd = bwd_mode(kernel, stride, x.shape, w.shape, x.dtype,
                       scale is not None, res=directive["defer"])
        bn = conv_schedule(kernel, stride, x.shape, w.shape, x.dtype,
                           directive["defer"])
        _note_conv(node, x.shape, True, "engaged", bwd)
        if directive["defer"]:
            return PendingConv(x, w, scale, shift, relu, kernel, stride,
                               bwd, bn)
        c, s, q = conv_block(x, w, scale, shift, None, kernel, stride, relu,
                             True, bwd, bn)
        return WithStats(c, s, q)
    # kind == _MESH_OTHER (tensor/seq-sharded) always lands here: XLA path
    # fallback: materialize the normalized input (cached on the marker) and
    # run the ordinary XLA conv (shared lowering from pallas_conv_bn)
    if _tm.enabled():
        # the mesh-shape branches above never reach gate(), so their
        # fallback must be counted here or these configs would read as
        # "zero fallbacks" in exactly the runs where fusion disengaged
        mesh_barred = (kind == _MESH_OTHER
                       or (kind == _MESH_DP and x.shape[0] % dp != 0))
        if mesh_barred:
            _tm.counter("fusion.fwd_fallback").inc()
        if _tm.tracing():
            if kind == _MESH_OTHER:
                reason = ("multi-device mesh without a pure 'data' axis: a "
                          "raw pallas_call would make GSPMD gather its "
                          "operands")
            elif mesh_barred:
                reason = ("batch %d not divisible by data-parallel degree %d"
                          % (x.shape[0], dp))
            else:
                shape = ((x.shape[0] // dp,) + x.shape[1:]
                         if kind == _MESH_DP else x.shape)
                _, reason = gate_explain(kernel, stride, shape, w.shape,
                                         x.dtype, scale is not None,
                                         res=directive["defer"])
            _note_conv(node, x.shape, False, reason)
    xn = v.materialize() if isinstance(v, Deferred) else x
    return _xla_conv(xn, w, None, None, None, kernel, stride, False)


# --------------------------------------------- inference (grad-less) variants
_warned_quant_env = False


def quant_mode():
    """The requested quantized-inference variant: ``off`` | ``bf16`` |
    ``int8`` (``MXNET_SERVE_QUANT``, docs/SERVING.md). Unrecognized values
    warn once and stay off."""
    env = os.environ.get("MXNET_SERVE_QUANT", "off").strip().lower()
    if env in ("", "0", "off", "none", "fp32", "float32"):
        return "off"
    if env in ("bf16", "bfloat16"):
        return "bf16"
    if env == "int8":
        return "int8"
    global _warned_quant_env
    if not _warned_quant_env:
        _warned_quant_env = True
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "MXNET_SERVE_QUANT=%r not recognized (off|bf16|int8); "
            "quantized inference stays off", env)
    return "off"


def _quant_conv_inputs(x, w, mode):
    """The quantized-inference input transform for one conv site.

    Deliberately traced INTO the compiled program: weights are executor
    inputs (arg_dict), so hoisting the transform would mean freezing them
    into the executable — a different ownership model the predict API's
    param-update path contradicts. The steady-state cost is O(|w|)
    (abs-max reduce + round) against the conv's O(|w|·B·H·W): under 1% at
    serving batch shapes, and XLA fuses the bf16 casts into the conv's
    operand reads.

    - ``bf16``: activations AND weights compute in bfloat16 (the MXU fast
      path; f32 accumulate comes from the conv's preferred_element_type).
    - ``int8``: weight-only symmetric per-output-channel quantization —
      weights snap to the 255-point int8 grid and dequantize through their
      per-channel scale. Compute stays in the activation dtype, so this
      measures the ACCURACY of int8 weights with fp32 math; an int8-MAC
      kernel can adopt the same grid later without changing results
      further.
    """
    if mode == "bf16":
        return x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    if mode == "int8":
        w32 = w.astype(jnp.float32)
        s = jnp.max(jnp.abs(w32), axis=tuple(range(1, w.ndim)),
                    keepdims=True) / 127.0
        s = jnp.where(s > 0, s, 1.0)
        wq = jnp.clip(jnp.round(w32 / s), -127, 127)
        return x, (wq * s).astype(w.dtype)
    return x, w


def _exec_conv_infer(directive, node, ins):
    """The grad-less execute path for a planned conv: moving-stat BN
    prologue stays folded (``_exec_bn`` inference branch), weights ride the
    quantized variant when requested, and ``gate(train=False)`` decides the
    Pallas-vs-XLA lowering with no backward budget in the predicate.
    Residual defers never engage here (the add runs as a plain elementwise
    — at inference the deferral saves no statistics pass), so no
    ``PendingConv`` marker is created."""
    v, w = ins
    kernel, stride = directive["kernel"], directive["stride"]
    if isinstance(v, Deferred):
        x, scale, shift, relu = v.raw, v.scale, v.shift, v.relu
    else:
        x, scale, shift, relu = resolve(v), None, None, False
    quant = quant_mode()
    x_c, w_c = _quant_conv_inputs(x, resolve(w), quant)
    kind, _, _ = _mesh_kind()
    if kind == _MESH_NONE:
        engaged = gate(kernel, stride, x_c.shape, w_c.shape, x_c.dtype,
                       scale is not None, res=False, train=False)
        reason = None
    else:
        engaged, reason = False, ("multi-device mesh: inference fusion "
                                  "runs single-device only")
        if _tm.enabled():
            _tm.counter("fusion.infer_fallback").inc()
    if engaged:
        _note_conv(node, x.shape, True,
                   "engaged (inference%s)"
                   % ("" if quant == "off" else ", quant=" + quant))
        # stats-free kernel variant: at is_train=False every downstream BN
        # folds its MOVING stats, so the training kernel's ssum/ssq
        # epilogue would be dead outputs the opaque pallas_call still
        # computes — return a plain tensor, not WithStats
        c = conv_block_infer(x_c, w_c, scale, shift, kernel, stride, relu)
        return c.astype(x.dtype)
    if _tm.tracing():
        if reason is None:
            _, reason = gate_explain(kernel, stride, x_c.shape, w_c.shape,
                                     x_c.dtype, scale is not None,
                                     res=False, train=False)
        _note_conv(node, x.shape, False, reason)
    # XLA fallback keeps the prologue folded into the conv's elementwise
    # preamble (no separate BN materialization) and the quantized weights
    c = _xla_conv(x_c, w_c, scale, shift, None, kernel, stride, relu)
    return c.astype(x.dtype)


def _exec_resadd(directive, ins):
    slot = directive["pending_slot"]
    pending, other = ins[slot], ins[1 - slot]
    if isinstance(pending, PendingConv):
        c, s, q = pending.run(resolve(other))
        return WithStats(c, s, q)
    return resolve(pending) + resolve(other)
