"""Colored console logging helper (reference: python/mxnet/log.py — same
public surface: ``getLogger(name, filename, filemode, level)`` plus the
level constants; the formatter is this repo's own, keyed on ANSI support).
"""
from __future__ import annotations

import logging
import sys

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL
NOTSET = logging.NOTSET

_COLORS = {
    logging.DEBUG: "\x1b[36m",     # cyan
    logging.INFO: "\x1b[32m",      # green
    logging.WARNING: "\x1b[33m",   # yellow
    logging.ERROR: "\x1b[31m",     # red
    logging.CRITICAL: "\x1b[35m",  # magenta
}
_RESET = "\x1b[0m"


class _LevelColorFormatter(logging.Formatter):
    """Prefix the level tag, colored when the stream is a terminal."""

    def __init__(self, colored):
        super().__init__("%(asctime)s %(message)s", "%m%d %H:%M:%S")
        self._colored = colored

    def format(self, record):
        tag = record.levelname[0]
        if self._colored and record.levelno in _COLORS:
            tag = _COLORS[record.levelno] + tag + _RESET
        return "%s %s" % (tag, super().format(record))


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """A configured logger: console (colored on TTYs) or ``filename``.
    Idempotent per logger: repeat calls reuse the existing configuration
    (and ``propagate`` is off) so records never print twice."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxtpu_configured", False):
        return logger
    if filename:
        handler: logging.Handler = logging.FileHandler(filename, filemode or "a")
        colored = False
    else:
        handler = logging.StreamHandler(sys.stderr)
        colored = getattr(sys.stderr, "isatty", lambda: False)()
    handler.setFormatter(_LevelColorFormatter(colored))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False
    logger._mxtpu_configured = True
    return logger
