"""Sharding rules: map symbol arguments to PartitionSpecs.

The reference distributes work by *where tensors live* (ctx lists, group2ctx
device placement, kvstore reduce targets). On TPU the equivalent decision is
*how arrays are laid out over the mesh*; XLA then materialises the collectives.
These rules are that translation table.
"""
from __future__ import annotations

from typing import Callable, Optional

__all__ = ["ShardingRules", "param_pspec"]


def param_pspec(name, shape, model_axis="model", model_size=1, min_shard_elems=2 ** 16):
    """Default tensor-parallel rule for a parameter.

    Shards the output dimension of large FC weights (``(out, in)``) and the
    vocab dimension of large embeddings over the ``model`` axis when the dim
    divides evenly; everything else (conv filters, biases, BN stats) is
    replicated — conv FLOPs are already parallel over the sharded batch, and
    small arrays cost more to shard than to replicate."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    if model_size <= 1 or len(shape) < 2:
        return P()
    if int(np.prod(shape)) < min_shard_elems:
        return P()
    if shape[0] % model_size == 0:
        return P(model_axis, *([None] * (len(shape) - 1)))
    return P()


class ShardingRules:
    """Bundle of sharding decisions for one training program.

    ``data_axis``/``model_axis`` name mesh axes. ``param_rule(name, shape) ->
    PartitionSpec`` decides parameter layout (default: ``param_pspec``).
    Data/label batches are sharded on dim 0 over the data axis."""

    def __init__(self, mesh, data_axis="data", model_axis="model",
                 param_rule: Optional[Callable] = None, seq_axis=None):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.model_axis = model_axis if model_axis in mesh.axis_names else None
        # opt-in (sequence-parallel training): shard dim 1 of batch inputs —
        # (B, T) token ids / labels — over this axis so activations enter the
        # network seq-sharded and ring attention never gathers the sequence
        self.seq_axis = seq_axis if seq_axis in (mesh.axis_names or ()) else None
        self._param_rule = param_rule

    @property
    def data_parallel_size(self):
        return self.mesh.shape[self.data_axis] if self.data_axis else 1

    @property
    def model_parallel_size(self):
        return self.mesh.shape[self.model_axis] if self.model_axis else 1

    def batch_spec(self, shape):
        from jax.sharding import PartitionSpec as P

        if not self.data_axis or not shape:
            return P()
        if self.seq_axis and len(shape) >= 2:
            return P(self.data_axis, self.seq_axis,
                     *([None] * (len(shape) - 2)))
        return P(self.data_axis, *([None] * (len(shape) - 1)))

    def param_spec(self, name, shape):
        from jax.sharding import PartitionSpec as P

        if self._param_rule is not None:
            return self._param_rule(name, shape)
        if not self.model_axis:
            return P()
        return param_pspec(name, shape, self.model_axis, self.model_parallel_size)

    def named(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)
