"""Sharding rules: map symbol arguments to PartitionSpecs.

The reference distributes work by *where tensors live* (ctx lists, group2ctx
device placement, kvstore reduce targets). On TPU the equivalent decision is
*how arrays are laid out over the mesh*; XLA then materialises the collectives.
These rules are that translation table.

The same rules drive two consumers: the SPMD trainer (which lays real arrays
out on a real ``jax.sharding.Mesh``) and the static sharding-plan lint
(``analysis/shard_lint.py``), which feeds an abstract ``MeshSpec`` through
the identical code path so the plan it criticises is the plan the trainer
would execute.
"""
from __future__ import annotations

from typing import Callable, Optional

__all__ = ["ShardingRules", "param_pspec", "shardable_dims",
           "MIN_SHARD_ELEMS"]

# the shard-or-replicate boundary (inclusive: prod(shape) >= this shards).
# One constant shared with analysis/shard_lint.py's GL401 threshold so the
# lint and the rule can never drift apart.
MIN_SHARD_ELEMS = 2 ** 16


def shardable_dims(shape, model_size):
    """Dims of a rank-2 parameter that divide evenly over ``model_size``,
    largest first — the candidate order ``param_pspec`` tries. Conv filters
    and other rank>2 params return () (replicated by policy: their FLOPs are
    already parallel over the sharded batch)."""
    if model_size <= 1 or len(shape) != 2:
        return ()
    # out-dim first (the classic Megatron column split); the remaining dims,
    # largest first, are the divisibility fallback — "the second-largest
    # shardable dim before giving up to full replication"
    order = [0] + sorted(range(1, len(shape)), key=lambda d: -shape[d])
    return tuple(d for d in order if shape[d] % model_size == 0)


def param_pspec(name, shape, model_axis="model", model_size=1,
                min_shard_elems=MIN_SHARD_ELEMS):
    """Default tensor-parallel rule for a parameter.

    Shards large rank-2 weights — FC ``(out, in)``, embedding ``(vocab,
    dim)`` — over the ``model`` axis: the out/vocab dim when it divides
    evenly, else (divisibility fallback) the other dim; only when neither
    divides does it give up to full replication. Everything else — conv
    filters (rank 4), biases, BN stats (rank 1) — is replicated: conv FLOPs
    are already parallel over the sharded batch, and small arrays cost more
    to shard than to replicate.

    Boundary: arrays with ``prod(shape) >= min_shard_elems`` are shardable
    (equality shards); strictly smaller arrays replicate.
    """
    import numpy as np
    from jax.sharding import PartitionSpec as P

    if model_size <= 1 or len(shape) != 2:
        return P()
    if int(np.prod(shape)) < min_shard_elems:
        return P()
    dims = shardable_dims(shape, model_size)
    if not dims:
        return P()
    spec = [None] * len(shape)
    spec[dims[0]] = model_axis  # best candidate wins; the rest are fallback
    return P(*spec)


class ShardingRules:
    """Bundle of sharding decisions for one training program.

    ``data_axis``/``model_axis`` name mesh axes. ``param_rule(name, shape) ->
    PartitionSpec`` decides parameter layout (default: ``param_pspec``).
    Data/label batches are sharded on dim 0 over the data axis.

    ``mesh`` may be a real ``jax.sharding.Mesh`` or an abstract
    ``parallel.mesh.MeshSpec`` — only ``axis_names``/``shape`` are read
    until ``named()`` (which needs real devices)."""

    def __init__(self, mesh, data_axis="data", model_axis="model",
                 param_rule: Optional[Callable] = None, seq_axis=None):
        self.mesh = mesh
        self.data_axis = data_axis if data_axis in mesh.axis_names else None
        self.model_axis = model_axis if model_axis in mesh.axis_names else None
        # opt-in (sequence-parallel training): shard dim 1 of batch inputs —
        # (B, T) token ids / labels — over this axis so activations enter the
        # network seq-sharded and ring attention never gathers the sequence
        self.seq_axis = seq_axis if seq_axis in (mesh.axis_names or ()) else None
        self._param_rule = param_rule

    @classmethod
    def infer_axes(cls, mesh, param_rule=None):
        """Rules for a mesh whose axes are not named data/model: the first
        axis NOT literally named 'model' is the data (batch) axis, and the
        model axis is the one named 'model' if present, else the second
        remaining axis. This is the graphlint ``--mesh dp=8,model=2``
        convention; a pure ``model=4`` mesh gets no data axis rather than a
        silently inverted plan."""
        names = tuple(mesh.axis_names)
        if "data" in names:
            data_axis = "data"
        else:
            data_axis = next((n for n in names if n != "model"), None)
        if "model" in names and "model" != data_axis:
            model_axis = "model"
        else:
            rest = [n for n in names if n != data_axis]
            model_axis = rest[0] if rest else "__none__"
        return cls(mesh, data_axis=data_axis or "__none__",
                   model_axis=model_axis, param_rule=param_rule)

    @property
    def data_parallel_size(self):
        return self.mesh.shape[self.data_axis] if self.data_axis else 1

    @property
    def model_parallel_size(self):
        return self.mesh.shape[self.model_axis] if self.model_axis else 1

    def batch_spec(self, shape):
        from jax.sharding import PartitionSpec as P

        if not self.data_axis or not shape:
            return P()
        if self.seq_axis and len(shape) >= 2:
            return P(self.data_axis, self.seq_axis,
                     *([None] * (len(shape) - 2)))
        return P(self.data_axis, *([None] * (len(shape) - 1)))

    def param_spec(self, name, shape):
        from jax.sharding import PartitionSpec as P

        if self._param_rule is not None:
            return self._param_rule(name, shape)
        if not self.model_axis:
            return P()
        return param_pspec(name, shape, self.model_axis, self.model_parallel_size)

    def named(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)
