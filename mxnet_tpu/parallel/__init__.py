"""SPMD parallelism over a TPU device mesh.

This package is the TPU-native answer to the reference's entire distribution
stack — DataParallelExecutorGroup's per-device executors
(python/mxnet/module/executor_group.py:77), the KVStore push/pull gradient sync
(src/kvstore/kvstore_local.h:22, kvstore_dist.h:32), and the ps-lite
worker/server topology (SURVEY.md §2.4/§2.5). Instead of one executor per
device plus an explicit reduce, the WHOLE training step — forward, backward,
gradient all-reduce, optimizer — is one jitted XLA computation over a
``jax.sharding.Mesh``:

  * batch axis sharded over the ``data`` mesh axis (data parallelism; the
    gradient psum is inserted by XLA's sharding propagation and rides ICI),
  * large weights optionally sharded over the ``model`` axis (tensor
    parallelism — the reference's group2ctx model parallelism re-imagined as
    sharding annotations instead of graph-partitioning + _CrossDeviceCopy),
  * ``jax.checkpoint`` rematerialisation standing in for
    MXNET_BACKWARD_DO_MIRROR (graph_executor.cc:210-223),
  * bf16 compute with fp32 master weights for the MXU fast path.

Multi-host: the same jit over a mesh spanning ``jax.devices()`` of all
processes (after ``jax.distributed.initialize``) IS the dist_tpu_sync design —
collectives ride ICI within a slice and DCN across slices; there is no
server/scheduler role to run.
"""
from .mesh import make_mesh, local_mesh, MeshSpec, parse_mesh_spec
from .sharding import ShardingRules, param_pspec, shardable_dims
from .optim import make_functional_optimizer
from .trainer import SPMDTrainer
from .autoplan import ParallelPlan, PlanError, plan_parallel

__all__ = [
    "make_mesh",
    "local_mesh",
    "MeshSpec",
    "parse_mesh_spec",
    "ShardingRules",
    "param_pspec",
    "shardable_dims",
    "make_functional_optimizer",
    "SPMDTrainer",
    "ParallelPlan",
    "PlanError",
    "plan_parallel",
]
