"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context support the reference never had (SURVEY.md §5.7 notes its only
answer to sequence length was bucketing): queries stay put while key/value
blocks rotate around the ``seq`` mesh axis via ``ppermute`` — each of the N
ring steps overlaps a local blockwise-attention matmul with the transfer of
the next block over ICI. Softmax is accumulated online (running max + running
denominator, flash-attention style), so the result is EXACT full attention
while no device ever materializes more than (T/N)² scores.

Usage: arrays sharded (B, T/N, H, D) on a mesh with a ``seq`` axis; call
``ring_attention(q, k, v, mesh, seq_axis='seq', causal=...)``.
"""
from __future__ import annotations


import numpy as np

__all__ = ["ring_attention", "local_blockwise_attention"]


def _block_attend(q, k, v, scale, mask):
    """One blockwise contribution: returns (unnormalized out, running max,
    running denom) pieces for online-softmax accumulation."""
    import jax.numpy as jnp

    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale  # (B,H,t,t')
    s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # (B,H,t)
    # guard all-masked rows (exp(-inf - -inf))
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)  # (B,H,t)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m_safe, l


def local_blockwise_attention(q, k, v, scale, causal, q_block, kv_block, block):
    """Attention of one query block against one kv block with global causal
    positions (q starts at q_block·block, k at kv_block·block)."""
    import jax.numpy as jnp

    t, s = q.shape[1], k.shape[1]
    if causal:
        q_pos = q_block * block + jnp.arange(t)
        k_pos = kv_block * block + jnp.arange(s)
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None]
    else:
        mask = jnp.ones((1, 1, t, s), bool)
    return _block_attend(q, k, v, scale, mask)


def ring_attention(q, k, v, mesh, seq_axis="seq", causal=False, scale=None,
                   batch_axis=None):
    """Exact attention with q/k/v sharded on the sequence axis.

    q, k, v: (B, T, H, D) jax arrays (global view), T divisible by the size of
    ``seq_axis``. Returns (B, T, H, D) with the same sharding as q.
    ``batch_axis`` additionally keeps dim 0 sharded (dp x sp execution —
    without it a batch-sharded operand would be gathered at the shard_map
    boundary)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[seq_axis]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    block = q.shape[1] // n

    def local(qb, kb, vb):
        # qb/kb/vb: (B, T/n, H, D) local shards
        my = jax.lax.axis_index(seq_axis)

        def step(carry, i):
            o, m, l, k_cur, v_cur = carry
            kv_idx = (my - i) % n  # block index currently held
            bo, bm, bl = local_blockwise_attention(
                qb, k_cur, v_cur, scale, causal, my, kv_idx, block)
            # online softmax merge
            m_new = jnp.maximum(m, bm)
            c1 = jnp.exp(m - m_new)
            c2 = jnp.exp(bm - m_new)
            o = o * c1[..., None].swapaxes(1, 2) + bo * c2[..., None].swapaxes(1, 2)
            l = l * c1 + bl * c2
            # rotate kv to the next device (overlaps with the next matmul)
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_next = jax.lax.ppermute(k_cur, seq_axis, perm)
            v_next = jax.lax.ppermute(v_cur, seq_axis, perm)
            return (o, m_new, l, k_next, v_next), None

        B, t, H, D = qb.shape
        # initial accumulators are constants; mark them device-varying so the
        # scan carry type matches the per-shard outputs (shard_map vma check)
        if hasattr(jax.lax, "pcast"):
            pvary = lambda x, axes: jax.lax.pcast(x, axes, to="varying")
        else:
            pvary = getattr(jax.lax, "pvary", lambda x, _: x)
        vary_axes = (seq_axis,) + ((batch_axis,) if batch_axis else ())
        o0 = pvary(jnp.zeros((B, t, H, D), "float32"), vary_axes)
        m0 = pvary(jnp.full((B, H, t), -jnp.inf, "float32"), vary_axes)
        l0 = pvary(jnp.zeros((B, H, t), "float32"), vary_axes)
        (o, m, l, _, _), _ = jax.lax.scan(
            step, (o0, m0, l0, kb.astype("float32"), vb.astype("float32")),
            jnp.arange(n))
        denom = jnp.where(l > 0, l, 1.0)
        out = o / denom[..., None].swapaxes(1, 2)
        return out.astype(qb.dtype)

    spec = P(batch_axis, seq_axis, None, None)
    from .mesh import shard_map_compat

    fn = shard_map_compat(local, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check=True)
    return fn(q, k, v)
