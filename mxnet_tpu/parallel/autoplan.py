"""Cost-model-driven auto-parallel planner: search dp × tp × pp.

The analysis stack already *predicts* the two quantities that decide a
distributed plan — GL402 emits bytes-moved per implicit reshard edge
(``analysis/shard_lint.py``) and GL5xx predicts peak HBM per device under
any PartitionSpec assignment (``analysis/memory_plan.py``) — but until now
a human picked the mesh and the specs by hand, and a model over budget was
just a GL501 error. This module closes the loop, the same move PR 9 made
for fusion (TVM's cost-model-driven search replacing hand tuning, PAPERS.md):

* ``plan_parallel(symbol, shapes, devices=8, ...)`` enumerates mesh
  factorizations ``data=dp, model=tp`` of the device count and per-param
  PartitionSpec assignments, scores every candidate with the predicted
  comm bytes per device per step, and returns the cheapest plan whose
  predicted peak HBM fits the budget.
* When NO dp × tp assignment fits, the axis set gains **pipeline stages**:
  the graph is cut at single-tensor boundaries into GPipe-style stages
  (``module.executor_group.PipelineExecutorGroup`` executes the microbatch
  schedule), and the planner sizes the stage count so each stage fits.
* The winner is a JSON-serializable ``ParallelPlan`` carrying the mesh,
  the per-param specs, the predicted bytes/peak, and every rejected
  alternative with the reason — a plan you can diff, not a heuristic you
  must trust. ``SPMDStepAdapter`` consumes it under ``MXNET_AUTOPLAN=1``;
  ``graphlint --autoplan`` dumps it over the model zoo.

Cost model (docs/PARALLEL_PLANNER.md):

  comm_bytes = 2 * reshard_bytes            # GL402 fwd edges; bwd mirrors
             + gradsync_bytes               # ring all-reduce of grads over
                                            #   dp: 2*(dp-1)/dp * grad bytes
                                            #   per device (the exact wire
                                            #   accounting kvstore_bucket
                                            #   counts into kvstore.bytes.*)
             + pipeline_bytes               # 2 * µ * boundary bytes (fwd
                                            #   activation + bwd cotangent)

  peak_bytes = the GL5xx liveness prediction; pipeline stages additionally
  hold (µ-1) extra boundary copies (the GPipe stash).

The search is deterministic: same symbol + shapes + devices + budget ⇒ the
same plan, bit for bit. Shape propagation (the expensive jax.eval_shape
walk) runs ONCE per graph; every candidate re-runs only the pure-Python
sharding propagation and liveness walk over the cached shapes.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["ParallelPlan", "PlanError", "plan_parallel", "split_symbol",
           "find_pipeline_cuts", "autoplan_enabled", "autoplan_budget_bytes",
           "autoplan_microbatches"]

# refinement breadth cap: per mesh, only this many largest shardable params
# get their alternative specs tried (the rest keep the base assignment)
_REFINE_CAP = 16

# ops whose FLOPs dominate a step: cost = out_elems * contraction size
# (weight elems / out features). Everything else is charged out_elems.
_MXU_FLOP_OPS = frozenset({"Convolution", "Deconvolution", "FullyConnected",
                           "dot", "batch_dot"})


class PlanError(MXNetError):
    """The planner cannot run at all (underdetermined shapes, bad input) —
    distinct from an *infeasible* plan, which is a structured result."""


# --------------------------------------------------------------------- env
def autoplan_enabled() -> bool:
    return os.environ.get("MXNET_AUTOPLAN", "").strip() == "1"


def autoplan_budget_bytes() -> Optional[int]:
    """Per-device peak-HBM budget for the planner: MXNET_AUTOPLAN_BUDGET_GB,
    falling back to the memlint budget (the two gates should agree unless
    told otherwise). Binary GiB, like every byte the report prints."""
    for var in ("MXNET_AUTOPLAN_BUDGET_GB", "MXNET_MEMLINT_BUDGET_GB"):
        raw = os.environ.get(var, "").strip()
        if raw:
            try:
                return int(float(raw) * 2 ** 30)
            except ValueError:
                continue
    return None


def autoplan_microbatches(default: int = 4) -> int:
    raw = os.environ.get("MXNET_PP_MICROBATCHES", "").strip()
    if raw:
        try:
            n = int(raw)
            if n >= 1:
                return n
        except ValueError:
            pass
    return default


# ---------------------------------------------------------------- the plan
class ParallelPlan:
    """One planner verdict. JSON-serializable; ``param_specs`` maps each
    parameter to its per-dim axis assignment (``None`` = replicated dim),
    e.g. ``{"fc1_weight": ["model", None]}``."""

    __slots__ = ("mesh", "devices", "param_specs", "pipeline_stages",
                 "microbatches", "stage_cuts", "predicted", "budget_bytes",
                 "feasible", "reason", "rejected", "naive", "stages")

    def __init__(self, mesh, devices, param_specs=None, pipeline_stages=1,
                 microbatches=1, stage_cuts=None, predicted=None,
                 budget_bytes=None, feasible=True, reason=None,
                 rejected=None, naive=None, stages=None):
        self.mesh = dict(mesh)
        self.devices = int(devices)
        self.param_specs = dict(param_specs or {})
        self.pipeline_stages = int(pipeline_stages)
        self.microbatches = int(microbatches)
        self.stage_cuts = list(stage_cuts or [])
        self.predicted = dict(predicted or {})
        self.budget_bytes = budget_bytes
        self.feasible = bool(feasible)
        self.reason = reason
        self.rejected = list(rejected or [])
        self.naive = naive
        self.stages = list(stages or [])

    def to_dict(self) -> dict:
        return {
            "mesh": dict(self.mesh),
            "devices": self.devices,
            "param_specs": {k: list(v) for k, v in
                            sorted(self.param_specs.items())},
            "pipeline_stages": self.pipeline_stages,
            "microbatches": self.microbatches,
            "stage_cuts": list(self.stage_cuts),
            "predicted": dict(self.predicted),
            "budget_bytes": self.budget_bytes,
            "feasible": self.feasible,
            "reason": self.reason,
            "rejected": list(self.rejected),
            "naive": self.naive,
            "stages": list(self.stages),
        }

    def to_json(self, indent=2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @classmethod
    def from_dict(cls, d: dict) -> "ParallelPlan":
        return cls(**{k: d.get(k) for k in
                      ("mesh", "devices", "param_specs", "pipeline_stages",
                       "microbatches", "stage_cuts", "predicted",
                       "budget_bytes", "feasible", "reason", "rejected",
                       "naive", "stages")})

    def param_rule(self):
        """A ``ShardingRules.param_rule`` callable applying this plan's
        per-param specs (unknown names fall back to replicated — the plan
        is authoritative about the graph it planned)."""
        from jax.sharding import PartitionSpec as P

        specs = self.param_specs

        def rule(name, shape):
            axes = specs.get(name)
            if not axes or not any(axes):
                return P()
            padded = list(axes) + [None] * (len(shape) - len(axes))
            return P(*padded[: len(shape)])

        return rule

    def summary(self) -> str:
        from ..analysis.shard_lint import fmt_bytes

        p = self.predicted
        mesh = ",".join("%s=%d" % kv for kv in self.mesh.items())
        head = "mesh[%s]" % mesh
        if self.pipeline_stages > 1:
            head += " x pp=%d (u=%d microbatches)" % (self.pipeline_stages,
                                                      self.microbatches)
        if not self.feasible:
            return "%s INFEASIBLE: %s" % (head, self.reason)
        sharded = sum(1 for v in self.param_specs.values() if any(v))
        return ("%s comm %s/step (reshard %s + gradsync %s + pipe %s), "
                "peak %s/device%s, %d sharded param(s)"
                % (head, fmt_bytes(p.get("comm_bytes", 0)),
                   fmt_bytes(p.get("reshard_bytes", 0)),
                   fmt_bytes(p.get("gradsync_bytes", 0)),
                   fmt_bytes(p.get("pipeline_bytes", 0)),
                   fmt_bytes(p.get("peak_bytes", 0)),
                   " (budget %s)" % fmt_bytes(self.budget_bytes)
                   if self.budget_bytes else "",
                   sharded))

    def __repr__(self):
        return "<ParallelPlan %s>" % self.summary()


# ------------------------------------------------------------ cost evaluator
class _Graph:
    """One symbol's shape-propagated analysis context, reusable across every
    candidate evaluation: shape/dtype propagation (the jax.eval_shape walk)
    runs once here; ``evaluate`` then re-runs only the pure-Python sharding
    propagation + memory liveness per candidate."""

    def __init__(self, symbol, shapes, types=None, bwd="stash", train=True,
                 label=""):
        from ..analysis.manager import GraphContext
        from ..analysis.shape_lint import shape_dtype_lint
        from ..analysis.shard_lint import batch_like_vars, _itemsize

        ctx = GraphContext(symbol, shape_hints=shapes, type_hints=types,
                           strict_shapes=True, bwd_policy=bwd, train=train)
        diags = shape_dtype_lint(ctx)
        errors = [d for d in diags if d.severity == "error"]
        if errors:
            raise PlanError(
                "cannot plan %s: shape/dtype propagation failed:\n%s"
                % (label or "symbol",
                   "\n".join(d.format() for d in errors[:4])))
        self.ctx = ctx
        self.label = label
        self.data_like = {n.name for n in batch_like_vars(ctx)}
        # trainable params (grads flow; aux BN stats carry no grad)
        self.params: List[Tuple[str, tuple, int]] = []
        for node in ctx.arg_nodes:
            if node.name in self.data_like:
                continue
            shape = ctx.var_shape.get(node.name)
            if shape is None:
                raise PlanError("cannot plan %s: parameter %r has no shape"
                                % (label or "symbol", node.name))
            nbytes = int(np.prod(shape)) * _itemsize(
                ctx.var_dtype.get(node.name))
            self.params.append((node.name, tuple(shape), nbytes))
        self.params.sort()
        # candidate-invariant FLOPs proxy per entry (see evaluate): the
        # per-candidate work is then only dividing by each output's shard
        # factor — this walk must not re-run per candidate
        self._entry_flops = []
        self._flops_total = 0.0
        for node in ctx.topo:
            if node.is_variable:
                continue
            k = 1.0
            if node.op in _MXU_FLOP_OPS and len(node.inputs) >= 2:
                wnode, woi = node.inputs[1]
                wsh = ctx.entry_shape.get((id(wnode), woi))
                if wsh:
                    k = float(np.prod(wsh)) / max(1, wsh[0])
            for i in range(node.num_outputs()):
                sh = ctx.entry_shape.get((id(node), i))
                if sh is None:
                    continue
                fl = float(np.prod(sh)) * k
                self._entry_flops.append(((id(node), i), fl))
                self._flops_total += fl

    def spec_options(self, tp: int) -> Dict[str, List[Optional[int]]]:
        """Per-param candidate dims over the model axis: ``None`` (replicate)
        plus every evenly-dividing dim of a large-enough rank-2 param, in
        ``shardable_dims`` preference order. A param none of whose dims
        divide gets [None] only — the GL401 replication fallback, by
        construction."""
        from .sharding import MIN_SHARD_ELEMS, shardable_dims

        out = {}
        for name, shape, nbytes in self.params:
            opts: List[Optional[int]] = [None]
            if tp > 1 and int(np.prod(shape)) >= MIN_SHARD_ELEMS:
                opts += list(shardable_dims(shape, tp))
            out[name] = opts
        return out

    def evaluate(self, mesh_axes: Dict[str, int],
                 assignment: Dict[str, int]) -> dict:
        """Score one (mesh, per-param-dim assignment) candidate. Returns a
        dict with comm/peak components and the GL401-style fallbacks."""
        from jax.sharding import PartitionSpec as P

        from ..analysis.memory_plan import plan_memory
        from ..analysis.shard_lint import (norm_spec, shard_plan_lint,
                                           spec_factor)
        from .mesh import MeshSpec
        from .sharding import ShardingRules

        ctx = self.ctx
        mesh = MeshSpec(mesh_axes)

        def rule(name, shape):
            d = assignment.get(name)
            if d is None:
                return P()
            spec = [None] * len(shape)
            spec[d] = "model"
            return P(*spec)

        ctx.mesh = mesh
        ctx.rules = ShardingRules(mesh, data_axis="data", model_axis="model",
                                  param_rule=rule)
        ctx.entry_spec = {}
        ctx.reshard_total_bytes = None
        ctx.reshard_edges = []
        ctx.memory_plan = None
        shard_plan_lint(ctx)
        plan = plan_memory(ctx)
        if plan is None:
            raise PlanError("cannot plan %s: shapes underdetermined"
                            % (self.label or "symbol"))
        reshard = int(ctx.reshard_total_bytes or 0)
        dp = int(mesh_axes.get("data", 1))
        # ---- compute-parallelism proxy: per-device FLOPs under the plan.
        # Without this term a dp=1 all-replicated mesh scores zero comm by
        # replicating ALL compute on every device — free by the comm metric,
        # useless on the hardware. The per-entry FLOPs (out_elems *
        # contraction size for MXU ops) are candidate-invariant and
        # precomputed in __init__; here each entry only divides by its
        # output's shard factor under this candidate's propagated specs.
        flops_dev = 0.0
        for entry, fl in self._entry_flops:
            sp = ctx.entry_spec.get(entry)
            f = spec_factor(sp, mesh) if sp else 1
            flops_dev += fl / max(1, f)
        # utilization bucket: log2 of the factor by which this plan's
        # per-device compute exceeds a perfect devices-way split. Coarse on
        # purpose — comm bytes decide among genuinely parallel plans; this
        # term only kills plans that waste whole halvings of the fleet.
        devices_total = int(np.prod(list(mesh_axes.values())))
        util = 1.0
        if self._flops_total > 0:
            util = max(1.0, flops_dev / (self._flops_total / devices_total))
        util_bucket = int(round(float(np.log2(util))))
        gradsync = 0
        for name, shape, nbytes in self.params:
            spec = norm_spec(rule(name, shape), len(shape))
            per_dev = nbytes // max(1, spec_factor(spec, mesh))
            if dp > 1:
                # ring all-reduce wire bytes per device — the exact formula
                # kvstore_bucket counts into kvstore.bytes.* at flush
                gradsync += int(2 * (dp - 1) * per_dev // dp)
        return {
            "mesh": dict(mesh_axes),
            "assignment": dict(assignment),
            "reshard_bytes": reshard,
            "gradsync_bytes": gradsync,
            "comm_bytes": 2 * reshard + gradsync,
            "peak_bytes": int(plan["per_device"]["peak"]),
            "util_bucket": util_bucket,
            "memory_plan": plan,
        }


def _divisor_meshes(devices: int) -> List[Tuple[int, int]]:
    """All (dp, tp) with dp*tp == devices, dp descending (pure data
    parallelism first — the naive baseline leads the enumeration)."""
    out = []
    for tp in range(1, devices + 1):
        if devices % tp == 0:
            out.append((devices // tp, tp))
    return out


def _assignment_specs(graph: _Graph, assignment: Dict[str, int]):
    """The JSON per-param spec view of an assignment."""
    specs = {}
    for name, shape, _ in graph.params:
        axes = [None] * len(shape)
        d = assignment.get(name)
        if d is not None:
            axes[d] = "model"
        specs[name] = axes
    return specs


def _cand_key(cand, budget):
    """Deterministic candidate order: feasible first, then the coarse
    compute-utilization bucket (a plan that wastes whole halvings of the
    fleet loses no matter its comm bill), then fewest predicted comm bytes,
    then lowest peak, then the larger data axis (ties go to the more
    conventional plan), then the mesh spelling."""
    feasible = budget is None or cand["peak_bytes"] <= budget
    return (not feasible, cand.get("util_bucket", 0), cand["comm_bytes"],
            cand["peak_bytes"], -cand["mesh"].get("data", 1),
            tuple(sorted(cand["mesh"].items())))


def _search_dp_tp(graph: _Graph, devices: int, budget: Optional[int]):
    """Phase 1: every dp×tp factorization × base spec policies, plus greedy
    per-param refinement on each tp>1 mesh's best base candidate. Returns
    (candidates sorted best-first, the naive all-dp candidate)."""
    candidates = []
    naive = None
    for dp, tp in _divisor_meshes(devices):
        mesh_axes = {"data": dp, "model": tp}
        options = graph.spec_options(tp)
        base = {"replicated": {}}
        if tp > 1:
            base["default"] = {n: o[1] for n, o in options.items()
                               if len(o) > 1}
            alt = {n: (o[2] if len(o) > 2 else o[1])
                   for n, o in options.items() if len(o) > 1}
            if alt != base["default"]:
                base["alt"] = alt
        best_here = None
        for label in sorted(base):
            cand = graph.evaluate(mesh_axes, base[label])
            cand["policy"] = label
            candidates.append(cand)
            if naive is None and tp == 1 and dp == devices:
                naive = cand
            if best_here is None or _cand_key(cand, budget) < _cand_key(
                    best_here, budget):
                best_here = cand
        if tp == 1:
            continue
        # greedy refinement: walk the largest shardable params (bounded by
        # _REFINE_CAP), trying each alternative dim incl. replication, and
        # keep any strict improvement — deterministic, no backtracking
        refinable = sorted(
            (n for n, o in options.items() if len(o) > 1),
            key=lambda n: (-next(b for p, _, b in graph.params if p == n), n)
        )[:_REFINE_CAP]
        cur = dict(best_here["assignment"])
        best = best_here
        for name in refinable:
            for opt in options[name]:
                if cur.get(name) == opt:
                    continue
                trial = dict(cur)
                if opt is None:
                    trial.pop(name, None)
                else:
                    trial[name] = opt
                cand = graph.evaluate(mesh_axes, trial)
                cand["policy"] = "refined"
                if _cand_key(cand, budget) < _cand_key(best, budget):
                    candidates.append(best)
                    best = cand
                    cur = trial
                else:
                    candidates.append(cand)
        if best is not best_here:
            candidates.append(best)
    # dedupe identical (mesh, assignment) keeping the best-scored instance
    seen = {}
    for cand in candidates:
        key = (tuple(sorted(cand["mesh"].items())),
               tuple(sorted(cand["assignment"].items())))
        if key not in seen or _cand_key(cand, budget) < _cand_key(
                seen[key], budget):
            seen[key] = cand
    ordered = sorted(seen.values(), key=lambda c: _cand_key(c, budget))
    return ordered, naive


# ----------------------------------------------------------- pipeline cuts
def find_pipeline_cuts(symbol, shapes, types=None, ctx=None):
    """Single-tensor graph boundaries eligible as pipeline-stage cuts.

    A position between two ops qualifies when exactly ONE activation entry
    crosses it (the boundary tensor GPipe ships between stages), no
    parameter/aux variable is consumed on both sides (stage-local weights —
    a param spanning stages would double-update), and the boundary is a
    floating tensor (cotangents must flow back through it).

    Returns a list of dicts sorted by topo position:
      {"entry": label, "position": i, "bytes": per-batch boundary bytes,
       "cum_param_bytes": trainable bytes at or before the cut}
    """
    from ..analysis.shard_lint import _itemsize, batch_like_vars

    if ctx is None:
        from ..analysis.manager import GraphContext
        from ..analysis.shape_lint import shape_dtype_lint

        ctx = GraphContext(symbol, shape_hints=shapes, type_hints=types,
                           strict_shapes=True)
        shape_dtype_lint(ctx)
    ops = [n for n in ctx.topo if not n.is_variable]
    if len(ops) < 2:
        return []
    data_like = {n.name for n in batch_like_vars(ctx)}
    head_set = {(id(n), oi) for n, oi in ctx.symbol._outputs
                if not n.is_variable}
    last_use: Dict[Tuple[int, int], int] = {}
    var_first: Dict[str, int] = {}
    var_last: Dict[str, int] = {}
    param_bytes_at: List[int] = []
    seen_params = set()
    cum = 0
    for k, node in enumerate(ops):
        for inp, oi in node.inputs:
            if inp.is_variable:
                var_first.setdefault(inp.name, k)
                var_last[inp.name] = k
                if inp.name not in data_like and inp.name not in seen_params:
                    seen_params.add(inp.name)
                    sh = ctx.var_shape.get(inp.name)
                    if sh is not None:
                        cum += int(np.prod(sh)) * _itemsize(
                            ctx.var_dtype.get(inp.name))
            else:
                last_use[(id(inp), oi)] = k
        param_bytes_at.append(cum)
    # param/aux vars spanning position k (stage-local weights required):
    # prefix-sum over each var's [first, last) consumer range — O(N + V)
    span_delta = [0] * (len(ops) + 1)
    for name in var_first:
        if name in data_like:
            continue
        if var_first[name] < var_last[name]:
            span_delta[var_first[name]] += 1
            span_delta[var_last[name]] -= 1
    spanning_at = []
    acc = 0
    for d in span_delta[:-1]:
        acc += d
        spanning_at.append(acc)

    # incremental live set: after op k, live = entries produced at <= k
    # still consumed later (or heads). One forward sweep, entries removed
    # at their last use — O(N) total instead of rescanning ops per k.
    dying_at = {}
    for e, k in last_use.items():
        if e not in head_set:
            dying_at.setdefault(k, []).append(e)
    entry_node = {}
    live = {}
    cuts = []
    for k in range(len(ops) - 1):
        node_k = ops[k]
        for e in dying_at.get(k, ()):
            live.pop(e, None)
        for i in range(node_k.num_outputs()):
            e = (id(node_k), i)
            entry_node[e] = (node_k, i)
            if last_use.get(e, -1) > k or e in head_set:
                live[e] = True
        if len(live) != 1:
            continue
        node, oi = entry_node[next(iter(live))]
        if spanning_at[k]:
            continue
        sh = ctx.entry_shape.get((id(node), oi))
        dt = ctx.entry_dtype.get((id(node), oi))
        if sh is None or not sh:
            continue
        try:
            if not np.issubdtype(np.dtype(dt), np.floating):
                continue
        except TypeError:
            continue
        label = node.name if node.num_outputs() == 1 else (
            "%s[%d]" % (node.name, oi))
        cuts.append({"entry": label, "position": k,
                     "bytes": int(np.prod(sh)) * _itemsize(dt),
                     "shape": tuple(sh), "dtype": np.dtype(dt).name,
                     "cum_param_bytes": param_bytes_at[k]})
    return cuts


def choose_cuts(symbol, shapes, types=None, n_stages=2):
    """Pick ``n_stages - 1`` cut entries for a pipeline split of ``symbol``
    (balancing trainable bytes per stage, the planner's policy). Raises
    ``PlanError`` when the graph offers no such partition."""
    from ..analysis.manager import GraphContext
    from ..analysis.shape_lint import shape_dtype_lint
    from ..analysis.shard_lint import _itemsize, batch_like_vars

    ctx = GraphContext(symbol, shape_hints=shapes, type_hints=types,
                       strict_shapes=True)
    shape_dtype_lint(ctx)
    cuts = find_pipeline_cuts(symbol, shapes, types, ctx=ctx)
    if len(cuts) < n_stages - 1:
        raise PlanError(
            "graph offers %d pipeline cut(s); %d stage(s) need %d"
            % (len(cuts), n_stages, n_stages - 1))
    data_like = {n.name for n in batch_like_vars(ctx)}
    total = 0
    for node in ctx.arg_nodes:
        if node.name in data_like:
            continue
        sh = ctx.var_shape.get(node.name)
        if sh is not None:
            total += int(np.prod(sh)) * _itemsize(ctx.var_dtype.get(node.name))
    chosen = _pick_cuts(cuts, n_stages, total)
    if chosen is None:
        raise PlanError("could not place %d distinct cuts" % (n_stages - 1))
    return [c["entry"] for c in chosen]


def _resolve_entry(symbol, label):
    """Find the (node, out_index) an entry label names."""
    name, oi = label, 0
    if label.endswith("]") and "[" in label:
        name, idx = label.rsplit("[", 1)
        oi = int(idx[:-1])
    for node in symbol._topo():
        if node.name == name and not node.is_variable:
            return node, oi
    raise PlanError("cut entry %r not found in the symbol" % label)


def split_symbol(symbol, cut_labels):
    """Split ``symbol`` into pipeline stages at the named cut entries.

    Returns ``(stage_symbols, boundary_names)``: stage k's graph rebuilds
    the original nodes (fresh ``_Node`` objects — the input symbol is never
    mutated), with stage k>0 consuming a new ``__pipe{k-1}__`` variable in
    place of the previous stage's boundary entry. Stage k<last has exactly
    one output: its boundary; the last stage keeps the original outputs.
    """
    from ..symbol import Symbol, _Node

    cut_entries = [_resolve_entry(symbol, lbl) for lbl in cut_labels]
    positions = {id(n): i for i, n in enumerate(symbol._topo())}
    if [positions[id(n)] for n, _ in cut_entries] != sorted(
            positions[id(n)] for n, _ in cut_entries):
        raise PlanError("cut entries must be in topological order")

    boundary_names = ["__pipe%d__" % i for i in range(len(cut_entries))]
    stages = []
    prev = None  # ((node, oi), boundary var name) of the upstream cut
    for k in range(len(cut_entries) + 1):
        stop = {}
        if prev is not None:
            (pn, poi), pname = prev
            stop[(id(pn), poi)] = _Node(None, pname, {}, [])
        memo = {}

        def rebuild(root):
            stack = [root]
            while stack:
                node = stack[-1]
                if id(node) in memo and memo[id(node)] is not None:
                    stack.pop()
                    continue
                pending = [inp for inp, oi in node.inputs
                           if (id(inp), oi) not in stop
                           and memo.get(id(inp)) is None]
                if pending:
                    stack.extend(pending)
                    memo.setdefault(id(node), None)
                    continue
                stack.pop()
                new = _Node(node.op, node.name, dict(node.attrs), [])
                for inp, oi in node.inputs:
                    if (id(inp), oi) in stop:
                        new.inputs.append((stop[(id(inp), oi)], 0))
                    else:
                        new.inputs.append((memo[id(inp)], oi))
                memo[id(node)] = new
            return memo[id(root)]

        if k < len(cut_entries):
            node, oi = cut_entries[k]
            heads = [(rebuild(node), oi)]
            prev = (cut_entries[k], boundary_names[k])
        else:
            heads = []
            for node, oi in symbol._outputs:
                if (id(node), oi) in stop:
                    heads.append((stop[(id(node), oi)], 0))
                else:
                    heads.append((rebuild(node), oi))
        stages.append(Symbol(heads))
    return stages, boundary_names


def _pick_cuts(cuts, n_stages, total_param_bytes):
    """Choose ``n_stages - 1`` cut positions balancing per-stage trainable
    bytes: for each target quantile, the candidate whose cumulative param
    bytes is nearest (earliest position breaks ties). Deterministic."""
    chosen = []
    used = set()
    for j in range(1, n_stages):
        target = total_param_bytes * j // n_stages
        best = None
        for c in cuts:
            if c["position"] in used:
                continue
            d = abs(c["cum_param_bytes"] - target)
            if best is None or (d, c["position"]) < (
                    abs(best["cum_param_bytes"] - target), best["position"]):
                best = c
        if best is None:
            return None
        used.add(best["position"])
        chosen.append(best)
    chosen.sort(key=lambda c: c["position"])
    if len({c["position"] for c in chosen}) != n_stages - 1:
        return None
    return chosen


def _scale_batch(shape, mu):
    if not shape or shape[0] % mu:
        return None
    return (shape[0] // mu,) + tuple(shape[1:])


def _search_pipeline(graph: _Graph, symbol, shapes, types, devices, budget,
                     bwd, microbatches, rejected):
    """Phase 2: no dp×tp assignment fits — partition into pp stages so each
    stage's predicted peak fits. Tries pp ascending (fewest stages first),
    each with every dp×tp factorization of the remaining devices."""
    ctx = graph.ctx
    cuts = find_pipeline_cuts(symbol, shapes, types, ctx=ctx)
    if not cuts:
        return None, ("no single-tensor pipeline cut exists in this graph "
                      "(every inter-op boundary carries more than one live "
                      "tensor or a stage-spanning parameter)")
    total_param_bytes = sum(b for _, _, b in graph.params)
    batch = None
    for name in sorted(graph.data_like):
        sh = ctx.var_shape.get(name)
        if sh:
            batch = sh[0]
            break
    if batch is None:
        return None, "no batch-carrying input to microbatch over"
    mu = microbatches
    while mu > 1 and batch % mu:
        mu -= 1

    reasons = []
    pps = [pp for pp in range(2, devices + 1) if devices % pp == 0]
    for pp in pps:
        if pp - 1 > len(cuts):
            reasons.append("pp=%d needs %d cuts, graph offers %d"
                           % (pp, pp - 1, len(cuts)))
            continue
        chosen = _pick_cuts(cuts, pp, total_param_bytes)
        if chosen is None:
            reasons.append("pp=%d: could not place %d distinct cuts"
                           % (pp, pp - 1))
            continue
        if any(c["shape"][0] % mu for c in chosen):
            reasons.append("pp=%d: a boundary dim 0 does not divide into "
                           "u=%d microbatches" % (pp, mu))
            continue
        labels = [c["entry"] for c in chosen]
        try:
            stage_syms, boundary_names = split_symbol(symbol, labels)
        except PlanError as exc:
            reasons.append("pp=%d: %s" % (pp, exc))
            continue
        # per-stage shape hints at MICROBATCH size: original data-like
        # inputs scale dim 0; stage k>0 additionally binds its boundary var
        stage_graphs = []
        ok = True
        for k, ssym in enumerate(stage_syms):
            hints, thints = {}, {}
            stage_inputs = set(ssym.list_inputs())
            for name in sorted(graph.data_like & stage_inputs):
                scaled = _scale_batch(ctx.var_shape.get(name), mu)
                if scaled is None:
                    ok = False
                    break
                hints[name] = scaled
                dt = ctx.var_dtype.get(name)
                if dt is not None:
                    thints[name] = dt
            if not ok:
                break
            if k > 0:
                bname = boundary_names[k - 1]
                scaled = _scale_batch(chosen[k - 1]["shape"], mu)
                if scaled is None:
                    ok = False
                    break
                hints[bname] = scaled
                # a bf16 boundary priced as default-f32 would double the
                # stage's activation/reshard bytes
                thints[bname] = np.dtype(chosen[k - 1]["dtype"])
            try:
                stage_graphs.append(_Graph(ssym, hints, thints, bwd=bwd,
                                           label="stage %d" % k))
            except PlanError as exc:
                reasons.append("pp=%d stage %d: %s" % (pp, k, exc))
                ok = False
                break
        if not ok:
            continue
        rem = devices // pp
        best = None
        for dp, tp in _divisor_meshes(rem):
            mesh_axes = {"data": dp, "model": tp}
            stage_cands = []
            for k, sg in enumerate(stage_graphs):
                options = sg.spec_options(tp)
                base = [{}]
                if tp > 1:
                    base.append({n: o[1] for n, o in options.items()
                                 if len(o) > 1})
                sbest = None
                for asg in base:
                    cand = sg.evaluate(mesh_axes, asg)
                    # this stage's boundaries: in-edge (k>0) and out-edge
                    # (k<last). stash = the GPipe (u-1) extra resident
                    # copies per device; pipe = fwd activation + bwd
                    # cotangent wire bytes per step (batch-sharded over dp)
                    stash = pipe = 0
                    for b in ([chosen[k - 1]] if k > 0 else []) + (
                            [chosen[k]] if k < pp - 1 else []):
                        stash += (mu - 1) * (b["bytes"] // mu) // max(1, dp)
                        pipe += 2 * (b["bytes"] // max(1, dp))
                    cand["peak_bytes"] += stash
                    cand["pipeline_bytes"] = pipe
                    cand["comm_bytes"] = (2 * cand["reshard_bytes"]
                                          + cand["gradsync_bytes"] + pipe)
                    if sbest is None or _cand_key(cand, budget) < _cand_key(
                            sbest, budget):
                        sbest = cand
                stage_cands.append(sbest)
            peak = max(c["peak_bytes"] for c in stage_cands)
            comm = max(c["comm_bytes"] for c in stage_cands)
            cand = {"mesh": mesh_axes, "pp": pp, "mu": mu,
                    "cuts": labels, "stage_cands": stage_cands,
                    "util_bucket": max(c.get("util_bucket", 0)
                                       for c in stage_cands),
                    "peak_bytes": peak, "comm_bytes": comm,
                    "reshard_bytes": max(c["reshard_bytes"]
                                         for c in stage_cands),
                    "gradsync_bytes": max(c["gradsync_bytes"]
                                          for c in stage_cands),
                    "pipeline_bytes": max(c.get("pipeline_bytes", 0)
                                          for c in stage_cands)}
            feasible = budget is None or peak <= budget
            if not feasible:
                rejected.append({
                    "mesh": dict(mesh_axes), "pipeline_stages": pp,
                    "comm_bytes": comm, "peak_bytes": peak,
                    "why": "max stage peak %d B exceeds budget %d B"
                           % (peak, budget)})
                continue
            if best is None or _cand_key(cand, budget) < _cand_key(
                    best, budget):
                best = cand
        if best is not None:
            return best, None
        reasons.append("pp=%d: no dp x tp layout of the remaining %d "
                       "device(s) fits a stage under the budget" % (pp, rem))
    return None, "; ".join(reasons) if reasons else \
        "no pipeline partitioning fits the budget"


# ----------------------------------------------------------------- planner
def plan_parallel(symbol, shapes, types=None, devices=8, budget_bytes=None,
                  budget_gb=None, bwd="stash", microbatches=None,
                  label="") -> ParallelPlan:
    """Search dp × tp × pp for the cheapest feasible plan.

    ``shapes``/``types`` are the ``infer_shape`` hint dicts at the GLOBAL
    batch size (the mesh splits it). ``budget_bytes``/``budget_gb`` arm the
    peak-HBM constraint (default: ``MXNET_AUTOPLAN_BUDGET_GB``, falling
    back to ``MXNET_MEMLINT_BUDGET_GB``; unset = unconstrained, the
    cheapest-comm plan wins outright). Pipeline stages are only searched
    when NO dp × tp assignment fits the budget.
    """
    if devices < 1:
        raise PlanError("devices must be >= 1, got %r" % (devices,))
    if budget_bytes is None:
        budget_bytes = (int(budget_gb * 2 ** 30) if budget_gb is not None
                        else autoplan_budget_bytes())
    mu_req = (microbatches if microbatches is not None
              else autoplan_microbatches())
    graph = _Graph(symbol, shapes, types, bwd=bwd, label=label)
    candidates, naive = _search_dp_tp(graph, devices, budget_bytes)
    best = candidates[0]
    naive_view = None
    if naive is not None:
        naive_view = {"mesh": dict(naive["mesh"]),
                      "comm_bytes": naive["comm_bytes"],
                      "peak_bytes": naive["peak_bytes"]}

    def _reject_row(cand, why):
        return {"mesh": dict(cand["mesh"]), "policy": cand.get("policy", ""),
                "comm_bytes": cand["comm_bytes"],
                "peak_bytes": cand["peak_bytes"], "why": why}

    feasible = (budget_bytes is None
                or best["peak_bytes"] <= budget_bytes)
    rejected = []
    seen_meshes = {tuple(sorted(best["mesh"].items()))}
    for cand in candidates[1:]:
        # one row per distinct mesh — candidates are best-first, so the
        # first occurrence is that mesh's strongest showing; the losing
        # refinement variants behind it add nothing a reader can act on
        mkey = tuple(sorted(cand["mesh"].items()))
        if mkey in seen_meshes:
            continue
        seen_meshes.add(mkey)
        if budget_bytes is not None and cand["peak_bytes"] > budget_bytes:
            why = ("peak %d B exceeds the %d B budget"
                   % (cand["peak_bytes"], budget_bytes))
        elif cand.get("util_bucket", 0) > best.get("util_bucket", 0):
            why = ("wastes compute parallelism: ~2^%d x the winner's "
                   "per-device FLOPs (replicated work)"
                   % cand["util_bucket"])
        elif cand["comm_bytes"] > best["comm_bytes"]:
            why = ("predicted comm %d B > winner's %d B"
                   % (cand["comm_bytes"], best["comm_bytes"]))
        else:
            why = ("tie-broken by (peak, data-axis size, mesh) against the "
                   "winner")
        rejected.append(_reject_row(cand, why))
    rejected = rejected[:24]  # the tail repeats itself; keep the plan small

    if feasible:
        return ParallelPlan(
            mesh=best["mesh"], devices=devices,
            param_specs=_assignment_specs(graph, best["assignment"]),
            predicted={"comm_bytes": best["comm_bytes"],
                       "reshard_bytes": best["reshard_bytes"],
                       "gradsync_bytes": best["gradsync_bytes"],
                       "pipeline_bytes": 0,
                       "peak_bytes": best["peak_bytes"]},
            budget_bytes=budget_bytes, feasible=True,
            rejected=rejected, naive=naive_view)

    # every dp x tp assignment is over budget -> pipeline stages
    pipe_rejected = list(rejected)
    pipe, why = _search_pipeline(graph, symbol, shapes, types, devices,
                                 budget_bytes, bwd, mu_req, pipe_rejected)
    if pipe is not None:
        specs = {}
        stages = []
        for k, sc in enumerate(pipe["stage_cands"]):
            specs.update(_assignment_specs_for(sc))
            stages.append({"stage": k,
                           "comm_bytes": sc["comm_bytes"],
                           "peak_bytes": sc["peak_bytes"],
                           "param_specs": {n: list(v) for n, v in
                                           _assignment_specs_for(sc).items()}})
        return ParallelPlan(
            mesh=pipe["mesh"], devices=devices, param_specs=specs,
            pipeline_stages=pipe["pp"], microbatches=pipe["mu"],
            stage_cuts=pipe["cuts"],
            predicted={"comm_bytes": pipe["comm_bytes"],
                       "reshard_bytes": pipe["reshard_bytes"],
                       "gradsync_bytes": pipe["gradsync_bytes"],
                       "pipeline_bytes": pipe["pipeline_bytes"],
                       "peak_bytes": pipe["peak_bytes"]},
            budget_bytes=budget_bytes, feasible=True,
            rejected=pipe_rejected, naive=naive_view, stages=stages)

    reason = ("no dp x tp assignment over %d device(s) fits the %d B "
              "budget (best: mesh %s at %d B peak), and the pipeline "
              "fallback found none either: %s"
              % (devices, budget_bytes,
                 ",".join("%s=%d" % kv for kv in best["mesh"].items()),
                 best["peak_bytes"], why))
    return ParallelPlan(
        mesh=best["mesh"], devices=devices,
        param_specs=_assignment_specs(graph, best["assignment"]),
        predicted={"comm_bytes": best["comm_bytes"],
                   "reshard_bytes": best["reshard_bytes"],
                   "gradsync_bytes": best["gradsync_bytes"],
                   "pipeline_bytes": 0,
                   "peak_bytes": best["peak_bytes"]},
        budget_bytes=budget_bytes, feasible=False, reason=reason,
        rejected=pipe_rejected, naive=naive_view)


def _assignment_specs_for(cand):
    """Per-param spec view of a stage candidate (shapes travel with the
    assignment only implicitly, so rebuild from the recorded dims)."""
    specs = {}
    for name, d in sorted(cand["assignment"].items()):
        # dims beyond d replicate; rank is at least d+1
        axes = [None] * (d + 1)
        axes[d] = "model"
        specs[name] = axes
    return specs
