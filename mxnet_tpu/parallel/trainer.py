"""SPMDTrainer: the whole training step as one sharded XLA computation.

Replaces the reference's hot path end to end (SURVEY.md §3.1): where
``Module.fit`` drove DataParallelExecutorGroup.forward/backward per device and
then KVStore push/pull per key (executor_group.py:355/481, model.py:88-116),
here forward + backward + gradient all-reduce + optimizer update compile into
a single ``jax.jit`` over a device mesh. The gradient psum never appears in
user code — params are laid out replicated (or model-axis-sharded) while the
batch is data-axis-sharded, so XLA's sharding propagation inserts the
all-reduce, batching all keys of the step into fused collectives riding ICI
(the hand-tuned priority queues of model.py:95-110 become the compiler's
latency hiding).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..base import MXNetError
from .optim import make_functional_optimizer
from .sharding import ShardingRules

__all__ = ["SPMDTrainer"]


class _TrainState:
    """The mutable training state (params / aux / optimizer state) in one
    cell, so several trainers can SHARE it: bucketing compiles one step per
    bucket shape while every bucket trains the same weights — the
    executor-per-bucket economics of the reference's shared memory pool
    (graph_executor.cc:348-351) with state sharing instead of buffer sharing.

    ``dirty`` flags device state newer than any host copy (checkpointing and
    exec-group refresh read it through SPMDStepAdapter.params_dirty)."""

    __slots__ = ("params", "aux", "opt_state", "dirty")

    def __init__(self):
        self.params = {}
        self.aux = {}
        self.opt_state = None
        self.dirty = False


class SPMDTrainer:
    """Train a Symbol over a mesh.

    Parameters
    ----------
    symbol : the network (loss heads as outputs, e.g. SoftmaxOutput).
    mesh : jax.sharding.Mesh (see parallel.make_mesh).
    data_names / label_names : input argument names.
    optimizer / optimizer_params : functional optimizer spec (optim.py).
    rules : ShardingRules (defaults to batch-on-'data', params replicated or
        tensor-sharded on 'model' when present).
    remat : rematerialise the forward during backward (jax.checkpoint) — the
        MXNET_BACKWARD_DO_MIRROR memory/compute trade. May also be a policy
        name: 'dots' (save matmul/conv outputs, recompute elementwise/BN —
        the bytes-for-FLOPs trade docs/PERF.md recommends on HBM-bound
        chips), 'nothing' (recompute everything), or True (save-nothing
        default checkpoint).
    compute_dtype : e.g. 'bfloat16' — cast inputs+params for compute, keep
        fp32 master weights and fp32 grads (MXU fast path).
    """

    def __init__(self, symbol, mesh, data_names=("data",),
                 label_names=("softmax_label",), optimizer="sgd",
                 optimizer_params=None, rules: Optional[ShardingRules] = None,
                 remat=False, compute_dtype=None):
        # remat accepts False | True | 'dots' | 'nothing'
        from ..executor import _GraphProgram

        self.symbol = symbol
        self.mesh = mesh
        self.rules = rules or ShardingRules(mesh)
        # conv+BN Pallas fusion: single-device meshes run the kernel
        # directly; pure-dp meshes run it per-shard under shard_map with
        # psum'd statistics (fusion._conv_block_sharded — a pallas_call has
        # no GSPMD partitioning rule of its own); tensor/seq-sharded meshes
        # fall back to the XLA lowering at trace time
        self._prog = _GraphProgram(symbol)
        self._remat = remat
        self._compute_dtype = np.dtype(compute_dtype) if compute_dtype else None

        arg_names = self._prog.arg_names
        self.input_names = [n for n in list(data_names) + list(label_names) if n in arg_names]
        self.param_names = [n for n in arg_names if n not in self.input_names]
        self.aux_names = self._prog.aux_names

        opt_kwargs = dict(optimizer_params or {})
        if isinstance(optimizer, str):
            # mirror make_functional_optimizer's default lr
            self._opt_static_lr = float(opt_kwargs.get("learning_rate", 0.01))
            self._opt_init, self._opt_apply = make_functional_optimizer(
                optimizer, **opt_kwargs)
        else:
            # pre-built (init, apply) pair, e.g. from functional_from_optimizer;
            # its learning rate is baked into the closure — pass lr=None
            # through so apply() uses it, unless the caller overrides per step
            self._opt_static_lr = None
            self._opt_init, self._opt_apply = optimizer

        self._state = _TrainState()
        self._step_fn = None
        self._megastep_fns = {}  # (n, with_lr) -> jitted N-step scan
        self._step_count = 0
        self._seed = 0
        self._base_key = None
        self._spans_cache = None
        # NaN/Inf anomaly guard (MXNET_ANOMALY_GUARD, docs/RESILIENCE.md):
        # mode is read when the step compiles; skipped_steps counts dropped
        # updates in skip mode
        self._anomaly_mode = None
        self.skipped_steps = 0

    # ----------------------------------------------------------- shared state
    @property
    def params(self) -> Dict:
        return self._state.params

    @params.setter
    def params(self, v):
        self._state.params = v

    @property
    def aux(self) -> Dict:
        return self._state.aux

    @aux.setter
    def aux(self, v):
        self._state.aux = v

    @property
    def opt_state(self):
        return self._state.opt_state

    @opt_state.setter
    def opt_state(self, v):
        self._state.opt_state = v

    def adopt_state(self, other: "SPMDTrainer"):
        """Share another trainer's state cell — the bucketing contract: same
        weights, a differently-shaped compiled step per bucket."""
        if set(self.param_names) != set(other.param_names) or \
                set(self.aux_names) != set(other.aux_names):
            raise MXNetError(
                "cannot share training state: bucket symbols disagree on "
                "parameter names")
        self._state = other._state

    # ------------------------------------------------------------------ init
    def init_params(self, data_shapes, label_shapes=None, initializer=None,
                    dtype="float32", seed=0):
        """Infer all shapes, initialize params on host, lay them out on the
        mesh per the sharding rules (committed arrays — jit respects them)."""
        import jax
        import jax.numpy as jnp

        from ..initializer import InitDesc, Xavier

        initializer = initializer or Xavier(factor_type="in", magnitude=2.0)
        hints = dict(data_shapes)
        hints.update(label_shapes or {})
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**hints)
        arg_map = dict(zip(self._prog.arg_names, arg_shapes))
        aux_map = dict(zip(self.aux_names, aux_shapes))
        attrs = self.symbol.attr_dict()
        from .. import random as _rnd

        _rnd.seed(seed)  # deterministic init regardless of prior RNG use

        def host_init(name, shape):
            arr = np.zeros(shape, dtype=dtype)
            desc = InitDesc(name, attrs.get(name, {}))
            # initializer mutates NDArray-likes; adapt via a tiny shim
            from ..ndarray import array as nd_array

            tmp = nd_array(arr)
            initializer(desc, tmp)
            return tmp.asnumpy()

        self.params = {}
        for name in self.param_names:
            spec = self.rules.param_spec(name, arg_map[name])
            self.params[name] = self._put_global(host_init(name, arg_map[name]), spec)
        self.aux = {}
        for name in self.aux_names:
            self.aux[name] = self._put_global(
                host_init(name, aux_map[name]), _replicated(self.rules))
        self.opt_state = self._opt_init(self.params)
        return self

    def _put_global(self, host, spec):
        """Place a full host copy of an array onto the mesh. Works across
        processes because every process holds the complete value and serves
        just its addressable shards."""
        import jax
        import jax.numpy as jnp

        host = np.asarray(host)
        sharding = self.rules.named(spec)
        if self._spans_processes:
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])
        return jax.device_put(jnp.asarray(host), sharding)

    # ------------------------------------------------------------------ step
    def _make_step_fn(self):
        """The pure one-step function ``step(params, aux, opt_state,
        inputs, base_key, lr)`` — traced by ``_build_step`` as the
        single-dispatch jit AND by ``_build_megastep`` as the scan body,
        so the N-step megastep is bitwise the same math as N separate
        steps (the per-step PRNG key folds the optimizer counter, which a
        guard-skipped step does not advance — seeded dropout etc. stays
        reproducible across any N partitioning)."""
        import jax
        import jax.numpy as jnp

        prog = self._prog
        input_names = self.input_names
        param_names = self.param_names
        aux_names = self.aux_names
        cdt = self._compute_dtype
        opt_apply = self._opt_apply

        def assemble(params, inputs):
            vals = []
            for n in prog.arg_names:
                v = inputs[n] if n in input_names else params[n]
                if cdt is not None and jnp.issubdtype(v.dtype, jnp.floating):
                    v = v.astype(cdt)
                vals.append(v)
            return tuple(vals)

        mesh = self.mesh

        def fwd(params, aux_tuple, inputs, rng):
            from .mesh import trace_mesh

            with trace_mesh(mesh):  # mesh-aware ops (ring attention) dispatch
                outs, new_aux = prog.interpret(assemble(params, inputs), aux_tuple, True, rng)
            if cdt is not None:
                new_aux = tuple(a.astype(o.dtype) if hasattr(o, "dtype") else a
                                for a, o in zip(new_aux, aux_tuple))
            return outs, new_aux

        if self._remat:
            if self._remat == "dots":
                # keep MXU results, re-derive cheap elementwise/norm chains
                # in backward instead of round-tripping them through HBM
                pol = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                fwd = jax.checkpoint(fwd, policy=pol)
            elif self._remat == "nothing":
                fwd = jax.checkpoint(
                    fwd, policy=jax.checkpoint_policies.nothing_saveable)
            else:
                fwd = jax.checkpoint(fwd, static_argnums=())

        from ..base import anomaly_guard_mode

        guard = anomaly_guard_mode() if param_names else None
        self._anomaly_mode = guard

        def step(params, aux, opt_state, inputs, base_key, lr):
            # derive the per-step key on device from the optimizer counter —
            # no host→device key transfer inside the training loop
            rng = jax.random.fold_in(base_key, opt_state["t"])
            aux_tuple = tuple(aux[n] for n in aux_names)

            def f(p):
                return fwd(p, aux_tuple, inputs, rng)

            outs, vjp_fn, new_aux = jax.vjp(f, params, has_aux=True)
            # loss heads (SoftmaxOutput & friends) ignore the incoming
            # cotangent, so ones is the identity head gradient
            cot = tuple(jnp.ones_like(o) for o in outs)
            (grads,) = vjp_fn(cot)
            grads = {k: g.astype(params[k].dtype) for k, g in grads.items()
                     if hasattr(g, "dtype") and g.dtype != jax.dtypes.float0}
            for k in params:
                if k not in grads:
                    grads[k] = jnp.zeros_like(params[k])
            new_params, new_opt = opt_apply(params, grads, opt_state, lr=lr)
            new_aux_d = dict(zip(aux_names, new_aux))
            if guard is None:
                return new_params, new_aux_d, new_opt, outs
            # anomaly guard: one all-finite bit per gradient, fused into
            # the step — if ANY is false the whole update (params, aux,
            # optimizer state incl. its counter) selects the OLD values,
            # so a dropped step is a true no-op on device. The per-key
            # vector goes back to the host so step() can name the first
            # offending key (key order: sorted, matching step()).
            finite_vec = jnp.stack(
                [jnp.all(jnp.isfinite(grads[k])) for k in sorted(grads)])
            ok = jnp.all(finite_vec)

            def _sel(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(ok, a, b), new, old)

            return (_sel(new_params, params),
                    _sel(new_aux_d, dict(zip(aux_names, aux_tuple))),
                    _sel(new_opt, opt_state), outs, finite_vec)

        return step

    def _build_step(self):
        import jax

        return jax.jit(self._make_step_fn(), donate_argnums=(0, 1, 2))

    def _build_megastep(self, n, with_lr):
        """N fused steps in ONE dispatch: a ``lax.scan`` of the SAME step
        body over batch-stacked inputs (leading axis N) and per-step lrs.
        The carry is (params, aux, opt_state); head outputs (and the
        anomaly guard's per-step finite vectors) stack along the scan
        axis. Dispatch-side state mutation stays identical to ``step`` —
        one jitted call, donated state."""
        import jax

        step = self._make_step_fn()
        guard = self._anomaly_mode

        def megastep(params, aux, opt_state, inputs, base_key, lrs):
            def body(carry, xs):
                p, a, o = carry
                inp, lr = xs if with_lr else (xs, None)
                res = step(p, a, o, inp, base_key, lr)
                if guard is None:
                    p2, a2, o2, outs = res
                    return (p2, a2, o2), (outs, ())
                p2, a2, o2, outs, fv = res
                return (p2, a2, o2), (outs, fv)

            xs = (inputs, lrs) if with_lr else inputs
            (p, a, o), (outs, fvs) = jax.lax.scan(
                body, (params, aux, opt_state), xs, length=n)
            if guard is None:
                return p, a, o, outs
            return p, a, o, outs, fvs

        return jax.jit(megastep, donate_argnums=(0, 1, 2))

    @property
    def _spans_processes(self):
        """True when the mesh covers devices of more than one process —
        inputs must then be assembled from per-process local shards."""
        if self._spans_cache is None:
            import jax

            self._spans_cache = any(d.process_index != jax.process_index()
                                    for d in self.mesh.devices.flat)
        return self._spans_cache

    def _place_input(self, v, spec):
        """Lay a host batch out on the mesh. Multi-host: each process holds
        its local rows — ``make_array_from_process_local_data`` glues them
        into one global array along the data axis (SPMD analogue of the
        per-worker batches the reference feeds through kvstore ranks)."""
        import jax

        if self._spans_processes:
            return jax.make_array_from_process_local_data(
                self.rules.named(spec), np.asarray(v))
        return jax.device_put(v, self.rules.named(spec))

    def step(self, data: Dict, label: Optional[Dict] = None, lr=None):
        """Run one training step; returns the head outputs (jax arrays).

        ``lr`` optionally overrides the optimizer's static learning rate for
        this step (drives lr schedules without retracing)."""
        import jax
        import jax.numpy as jnp

        if not self.params and self.param_names:
            raise MXNetError("call init_params first")
        if self._step_fn is None:
            self._step_fn = self._build_step()
        from .. import telemetry as _tm

        sp = _tm.NULL_SPAN
        if _tm.enabled():
            _tm.counter("trainer.step").inc()
            _tm.counter("trainer.dispatches").inc()
            _tm.gauge("train.steps_per_dispatch").set(1)
            # host-side dispatch time only: the XLA step itself is async
            sp = _tm.span("trainer.step", n=self._step_count)
        with sp:
            placed = self._place_batch(data, label)
            if lr is None:
                lr = self._opt_static_lr  # may stay None → apply() uses its own lr
            self._step_count += 1
            res = self._step_fn(
                self.params, self.aux, self.opt_state, placed, self._base_key,
                None if lr is None else jnp.asarray(lr, "float32"))
            if self._anomaly_mode is None:
                self.params, self.aux, self.opt_state, outs = res
            else:
                self.params, self.aux, self.opt_state, outs, finite = res
                self._check_anomaly(finite)
        return outs

    def step_many(self, data_list, label_list=None, lrs=None):
        """Run N training steps in ONE dispatch (the training megastep,
        docs/PERF.md §megasteps): the N batches are stacked on a leading
        axis and scanned through the same step body ``step`` traces, so
        the resulting weights are bitwise what N ``step`` calls produce —
        including NaN-guard skipped steps, which where-select the old
        state inside the scan exactly as they do outside it.

        ``lrs`` is an optional per-step learning-rate list (None entries
        fall back to the optimizer's static lr). Returns a list of N
        per-step head-output tuples (device arrays, sliced from the
        stacked scan outputs). Multi-process meshes are rejected:
        process-local shard assembly has no stacked equivalent."""
        import jax.numpy as jnp

        n = len(data_list)
        if n == 0:
            return []
        if not self.params and self.param_names:
            raise MXNetError("call init_params first")
        if n == 1:
            lr = lrs[0] if lrs else None
            outs = self.step(data_list[0],
                             (label_list or [None])[0], lr=lr)
            return [outs]
        if self._spans_processes:
            raise MXNetError(
                "step_many: multi-process meshes are not supported (the "
                "stacked batch cannot be assembled from process-local "
                "shards) — set MXNET_TRAIN_MEGASTEP_N=1")
        with_lr = False
        lr_vals = None
        if lrs is not None or self._opt_static_lr is not None:
            vals = [(None if lrs is None else lrs[i]) for i in range(n)]
            vals = [self._opt_static_lr if v is None else v for v in vals]
            if any(v is None for v in vals):
                raise MXNetError(
                    "step_many: per-step lr required when the optimizer "
                    "has no static learning rate")
            with_lr = True
            lr_vals = jnp.asarray(np.asarray(vals, np.float32))
        key = (n, with_lr)
        fn = self._megastep_fns.get(key)
        if fn is None:
            if self._step_fn is None:
                # step() and step_many() share _anomaly_mode; build the
                # single-step jit first so both read the same guard mode
                self._step_fn = self._build_step()
            fn = self._megastep_fns[key] = self._build_megastep(n, with_lr)
        from .. import telemetry as _tm

        sp = _tm.NULL_SPAN
        if _tm.enabled():
            _tm.counter("trainer.step").inc(n)
            _tm.counter("trainer.megastep").inc()
            _tm.counter("trainer.dispatches").inc()
            _tm.gauge("train.steps_per_dispatch").set(n)
            sp = _tm.span("trainer.megastep", n=self._step_count, steps=n)
        with sp:
            placed = self._place_batch_stacked(data_list, label_list)
            self._step_count += n
            res = fn(self.params, self.aux, self.opt_state, placed,
                     self._base_key, lr_vals)
            if self._anomaly_mode is None:
                self.params, self.aux, self.opt_state, outs = res
            else:
                self.params, self.aux, self.opt_state, outs, fvs = res
                self._check_anomaly(fvs)
        return [tuple(o[i] for o in outs) for i in range(n)]

    def _place_batch_stacked(self, data_list, label_list=None):
        """Stack N host batches on a leading scan axis and lay them out on
        the mesh: per-step sharding is the usual batch spec, the scan axis
        is unsharded (``P(None, *batch_spec)``)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        n = len(data_list)
        labels = label_list or [None] * n
        placed = {}
        for name in self.input_names:
            rows = []
            for i in range(n):
                inputs = dict(data_list[i])
                inputs.update(labels[i] or {})
                if name not in inputs:
                    raise MXNetError("missing input %r" % name)
                rows.append(np.asarray(inputs[name]))
            stacked = np.stack(rows, axis=0)
            spec = self.rules.batch_spec(rows[0].shape)
            sspec = P(*((None,) + tuple(spec)))
            placed[name] = jax.device_put(jnp.asarray(stacked),
                                          self.rules.named(sspec))
        if getattr(self, "_base_key", None) is None:
            self._base_key = jax.device_put(
                jax.random.PRNGKey(self._seed),
                self.rules.named(_replicated(self.rules)))
        return placed

    def _check_anomaly(self, finite_vec):
        """Host half of the anomaly guard: the device side already
        where-selected the old state if any gradient was non-finite; here
        the per-key vector is read back (this synchronizes the step — the
        guard trades async dispatch for the check, docs/RESILIENCE.md) to
        count the skip or raise naming the first offending key.

        A megastep hands a (N, keys) stack — one row per scanned step,
        checked in step order. The device side already skip-selected each
        offending step individually; in raise mode the error surfaces
        after the whole dispatch (the scan cannot stop mid-flight)."""
        from .. import telemetry as _tm

        fv = np.asarray(finite_vec)
        if fv.all():
            return
        if fv.ndim == 2:
            for row in fv:
                self._check_anomaly(row)
            return
        bad = sorted(self.params)[int(np.argmin(fv))]
        if self._anomaly_mode == "raise":
            raise MXNetError(
                "anomaly guard: non-finite (NaN/Inf) gradient for "
                "parameter %r at step %d — the fused step left params/"
                "optimizer state UN-updated (MXNET_ANOMALY_GUARD=raise)"
                % (bad, self._step_count))
        self.skipped_steps += 1
        if _tm.enabled():
            _tm.counter("trainer.skipped_steps").inc()
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "anomaly guard: dropped step %d — non-finite gradient, first "
            "offending key %r (%d step(s) skipped so far)",
            self._step_count, bad, self.skipped_steps)

    def _place_batch(self, data, label=None):
        """Lay one batch out on the mesh per the sharding rules (shared by
        ``step`` and ``cost_analysis``)."""
        import jax
        import jax.numpy as jnp

        inputs = dict(data)
        inputs.update(label or {})
        placed = {}
        for n in self.input_names:
            if n not in inputs:
                raise MXNetError("missing input %r" % n)
            v = inputs[n]
            v = v if hasattr(v, "dtype") and not isinstance(v, np.ndarray) else jnp.asarray(np.asarray(v))
            placed[n] = self._place_input(v, self.rules.batch_spec(v.shape))
        if getattr(self, "_base_key", None) is None:
            self._base_key = jax.device_put(
                jax.random.PRNGKey(self._seed), self.rules.named(_replicated(self.rules)))
        return placed

    def cost_analysis(self, data, label=None):
        """XLA's cost analysis of the compiled training step — a dict with
        ``flops`` and ``bytes accessed`` (the quantities docs/PERF.md's
        roofline argument rests on). Lowers, does NOT execute the step.
        Note: the AOT lower/compile here does not share jit's executable
        cache, so this pays one extra compile — a perf-lab cost, not a
        training-loop one."""
        import jax.numpy as jnp

        if not self.params and self.param_names:
            raise MXNetError("call init_params first")
        if self._step_fn is None:
            self._step_fn = self._build_step()
        placed = self._place_batch(data, label)
        lr = self._opt_static_lr
        lowered = self._step_fn.lower(
            self.params, self.aux, self.opt_state, placed, self._base_key,
            None if lr is None else jnp.asarray(lr, "float32"))
        cost = lowered.compile().cost_analysis()
        return cost[0] if isinstance(cost, (list, tuple)) else cost

    # ------------------------------------------------------------------ misc
    def get_params(self):
        """Gather params/aux to host numpy (for checkpointing / Module interop)."""
        import jax

        if self._spans_processes:
            from jax.experimental.multihost_utils import process_allgather

            fetch = lambda v: np.asarray(process_allgather(v, tiled=True))
        else:
            fetch = lambda v: np.asarray(jax.device_get(v))
        gather = lambda d: {k: fetch(v) for k, v in d.items()}
        return gather(self.params), gather(self.aux)

    def set_params(self, arg_params, aux_params=None):
        for name, v in (arg_params or {}).items():
            if name in self.param_names:
                spec = self.rules.param_spec(name, np.shape(v))
                self.params[name] = self._put_global(np.asarray(v), spec)
        for name, v in (aux_params or {}).items():
            if name in self.aux_names:
                self.aux[name] = self._put_global(np.asarray(v), _replicated(self.rules))
        if self.opt_state is None and self.params:
            self.opt_state = self._opt_init(self.params)


def _replicated(rules):
    from jax.sharding import PartitionSpec as P

    return P()
