"""Device-mesh construction helpers + the trace-time mesh context.

``trace_mesh``/``current_trace_mesh`` let mesh-aware ops (ring attention
dispatch in ops/attention.py) discover the SPMD mesh while the trainer's
step is being traced — the op registry's apply signature carries no mesh,
and threading one through every op would leak parallelism into the single-
device API."""
from __future__ import annotations

import contextlib
import contextvars

import numpy as np

__all__ = ["make_mesh", "local_mesh", "trace_mesh", "current_trace_mesh",
           "shard_map_compat", "MeshSpec", "parse_mesh_spec"]


class MeshSpec:
    """Device-free mesh description: axis names and sizes, nothing else.

    The static-analysis passes (analysis/shard_lint.py, memory_plan.py)
    reason about a *planned* mesh — ``dp=8,model=2`` on a CPU dev box that
    has no 16 devices to build a real ``jax.sharding.Mesh`` from. A
    ``MeshSpec`` carries exactly the two attributes ``ShardingRules`` and
    the lint passes read (``axis_names``, ``shape``), so the same rules
    object drives both the real trainer mesh and the abstract plan."""

    __slots__ = ("shape", "axis_names")

    def __init__(self, axes):
        """``axes``: dict name -> size (ordering is axis order), or an
        iterable of (name, size) pairs."""
        self.shape = {str(k): int(v) for k, v in dict(axes).items()}
        if not self.shape:
            raise ValueError("MeshSpec needs at least one axis")
        for name, size in self.shape.items():
            if size < 1:
                raise ValueError("mesh axis %r has size %d" % (name, size))
        self.axis_names = tuple(self.shape)

    @property
    def size(self):
        return int(np.prod(list(self.shape.values())))

    @classmethod
    def of(cls, mesh):
        """Coerce a real ``jax.sharding.Mesh`` (or another MeshSpec) to a
        MeshSpec — the lint passes' common currency."""
        if isinstance(mesh, cls):
            return mesh
        return cls({name: mesh.shape[name] for name in mesh.axis_names})

    def __repr__(self):
        return "MeshSpec(%s)" % ",".join(
            "%s=%d" % (n, s) for n, s in self.shape.items())


def parse_mesh_spec(spec):
    """Parse ``"dp=8,model=2"`` (the graphlint ``--mesh`` syntax) into a
    ``MeshSpec``. Also accepts a dict or an existing MeshSpec/Mesh."""
    if isinstance(spec, str):
        axes = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    "--mesh expects AXIS=SIZE[,AXIS=SIZE...], got %r" % spec)
            name, size = part.split("=", 1)
            name = name.strip()
            if name in axes:
                # a typo'd 'dp=2,dp=8' must not silently lint a wrong mesh
                raise ValueError("mesh axis %r given twice in %r"
                                 % (name, spec))
            axes[name] = int(size)
        return MeshSpec(axes)
    if isinstance(spec, dict):
        return MeshSpec(spec)
    return MeshSpec.of(spec)


def shard_map_compat(f, mesh, in_specs, out_specs, check=False):
    """``shard_map`` across the jax versions this repo meets: new jax
    exposes ``jax.shard_map`` (replication checker flag ``check_vma``),
    0.4.x has ``jax.experimental.shard_map.shard_map`` (``check_rep``).
    ``check=False`` disables the checker either way — the callers' specs
    are simple enough to state outright, and pallas_call out_shapes carry
    no vma annotation for the new checker to verify."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)

_TRACE_MESH = contextvars.ContextVar("mxtpu_trace_mesh", default=None)


def current_trace_mesh():
    """The mesh of the SPMD step currently being traced, or None."""
    return _TRACE_MESH.get()


@contextlib.contextmanager
def trace_mesh(mesh):
    tok = _TRACE_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _TRACE_MESH.reset(tok)


def make_mesh(shape=None, axis_names=("data", "model"), devices=None):
    """Build a ``jax.sharding.Mesh``.

    ``shape`` maps axis name → size (dict) or is a tuple aligned with
    ``axis_names``. Unspecified trailing axes default to size 1; a single
    ``-1`` entry absorbs the remaining devices. With no shape at all, every
    device lands on the first axis (pure data parallelism)."""
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        sizes = [n] + [1] * (len(axis_names) - 1)
    elif isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        sizes = list(shape.values())
    else:
        sizes = list(shape)
        if len(sizes) < len(axis_names):
            sizes += [1] * (len(axis_names) - len(sizes))
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError("mesh shape %s does not divide %d devices" % (sizes, n))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError("mesh shape %s != %d devices" % (sizes, n))
    dev_array = np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(dev_array, tuple(axis_names))


def local_mesh(n_devices=None, axis_names=("data",)):
    """Mesh over the first ``n_devices`` local devices, one axis by default."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return make_mesh((len(devices),) + (1,) * (len(axis_names) - 1), axis_names, devices)
