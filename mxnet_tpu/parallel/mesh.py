"""Device-mesh construction helpers + the trace-time mesh context.

``trace_mesh``/``current_trace_mesh`` let mesh-aware ops (ring attention
dispatch in ops/attention.py) discover the SPMD mesh while the trainer's
step is being traced — the op registry's apply signature carries no mesh,
and threading one through every op would leak parallelism into the single-
device API."""
from __future__ import annotations

import contextlib
import contextvars

import numpy as np

__all__ = ["make_mesh", "local_mesh", "trace_mesh", "current_trace_mesh",
           "shard_map_compat"]


def shard_map_compat(f, mesh, in_specs, out_specs, check=False):
    """``shard_map`` across the jax versions this repo meets: new jax
    exposes ``jax.shard_map`` (replication checker flag ``check_vma``),
    0.4.x has ``jax.experimental.shard_map.shard_map`` (``check_rep``).
    ``check=False`` disables the checker either way — the callers' specs
    are simple enough to state outright, and pallas_call out_shapes carry
    no vma annotation for the new checker to verify."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)

_TRACE_MESH = contextvars.ContextVar("mxtpu_trace_mesh", default=None)


def current_trace_mesh():
    """The mesh of the SPMD step currently being traced, or None."""
    return _TRACE_MESH.get()


@contextlib.contextmanager
def trace_mesh(mesh):
    tok = _TRACE_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _TRACE_MESH.reset(tok)


def make_mesh(shape=None, axis_names=("data", "model"), devices=None):
    """Build a ``jax.sharding.Mesh``.

    ``shape`` maps axis name → size (dict) or is a tuple aligned with
    ``axis_names``. Unspecified trailing axes default to size 1; a single
    ``-1`` entry absorbs the remaining devices. With no shape at all, every
    device lands on the first axis (pure data parallelism)."""
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        sizes = [n] + [1] * (len(axis_names) - 1)
    elif isinstance(shape, dict):
        axis_names = tuple(shape.keys())
        sizes = list(shape.values())
    else:
        sizes = list(shape)
        if len(sizes) < len(axis_names):
            sizes += [1] * (len(axis_names) - len(sizes))
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError("mesh shape %s does not divide %d devices" % (sizes, n))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError("mesh shape %s != %d devices" % (sizes, n))
    dev_array = np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(dev_array, tuple(axis_names))


def local_mesh(n_devices=None, axis_names=("data",)):
    """Mesh over the first ``n_devices`` local devices, one axis by default."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return make_mesh((len(devices),) + (1,) * (len(axis_names) - 1), axis_names, devices)
