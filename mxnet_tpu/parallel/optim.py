"""Functional optimizers for the in-step SPMD update.

The imperative ``mxnet_tpu.optimizer`` classes update NDArrays key-by-key —
fine for the Module/KVStore path, but the SPMD trainer needs the update
*inside* the jitted step (the reference's ``update_on_kvstore`` moved the
optimizer onto ps-lite servers, kvstore_dist_server.h:164-198; SPMD moves it
into the compiled program). These return pure ``(init, apply)`` pairs over
parameter dicts, mirroring the fused-op semantics of ops/optimizer_ops.py.

``apply(params, grads, state, lr=None)`` — ``lr`` is an optional traced
scalar overriding the static learning rate, so an ``mx.lr_scheduler`` can
drive the fused step without retracing (the schedule value is just another
input to the compiled program).
"""
from __future__ import annotations

__all__ = ["make_functional_optimizer", "functional_from_optimizer"]


def make_functional_optimizer(name="sgd", learning_rate=0.01, wd=0.0,
                              rescale_grad=1.0, clip_gradient=None,
                              momentum=0.9, beta1=0.9, beta2=0.999,
                              epsilon=1e-8, lr_mult=None, wd_mult=None,
                              **_ignored):
    """Return ``(init_fn, apply_fn)``.

    ``init_fn(params) -> state``; ``apply_fn(params, grads, state, lr=None)
    -> (new_params, new_state)``. All pure jax, so the whole update fuses
    into the training step's XLA computation. ``lr_mult``/``wd_mult`` are
    optional name→float dicts (reference: optimizer.py _get_lr/_get_wd)."""
    import jax.numpy as jnp

    lr_mult = dict(lr_mult or {})
    wd_mult = dict(wd_mult or {})

    def prep(g):
        g = g * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return g

    def k_lr(lr_now, k):
        return lr_now * lr_mult.get(k, 1.0)

    def k_wd(k):
        return wd * wd_mult.get(k, 1.0)

    if name in ("sgd", "nag"):
        use_mom = momentum > 0

        def init(params):
            state = {"t": jnp.zeros((), "int32")}
            if use_mom:
                state["mom"] = {k: jnp.zeros_like(v) for k, v in params.items()}
            return state

        def apply(params, grads, state, lr=None):
            lr_now = learning_rate if lr is None else lr
            new_params, new_mom = {}, {}
            for k, w in params.items():
                g = prep(grads[k]) + k_wd(k) * w
                if not use_mom:
                    new_params[k] = w - k_lr(lr_now, k) * g
                    continue
                m = momentum * state["mom"][k] - k_lr(lr_now, k) * g
                new_mom[k] = m
                if name == "nag":  # Nesterov lookahead (reference optimizer.py NAG)
                    new_params[k] = w + momentum * m - k_lr(lr_now, k) * g
                else:
                    new_params[k] = w + m
            new_state = {"t": state["t"] + 1}
            if use_mom:
                new_state["mom"] = new_mom
            return new_params, new_state

        return init, apply

    if name == "adam":

        def init(params):
            return {
                "t": jnp.zeros((), "int32"),
                "m": {k: jnp.zeros_like(v) for k, v in params.items()},
                "v": {k: jnp.zeros_like(v) for k, v in params.items()},
            }

        def apply(params, grads, state, lr=None):
            lr_now = learning_rate if lr is None else lr
            t = state["t"] + 1
            # bias-corrected step size, as the reference Adam computes lr_t
            correction = jnp.sqrt(1.0 - beta2 ** t.astype("float32")) / (
                1.0 - beta1 ** t.astype("float32"))
            new_params, new_m, new_v = {}, {}, {}
            for k, w in params.items():
                g = prep(grads[k]) + k_wd(k) * w
                m = beta1 * state["m"][k] + (1 - beta1) * g
                v = beta2 * state["v"][k] + (1 - beta2) * g * g
                new_m[k], new_v[k] = m, v
                new_params[k] = w - k_lr(lr_now, k) * correction * m / (
                    jnp.sqrt(v) + epsilon)
            return new_params, {"t": t, "m": new_m, "v": new_v}

        return init, apply

    raise ValueError("unknown functional optimizer %r (have sgd/nag/adam)" % name)


_SUPPORTED_CLASSES = {"SGD": "sgd", "NAG": "nag", "Adam": "adam"}


def functional_from_optimizer(optimizer, param_names):
    """Lower an ``mxnet_tpu.optimizer.Optimizer`` instance to a functional
    ``(init, apply, lr_of_step)`` triple, or return ``None`` when its class
    or per-param configuration has no in-step equivalent.

    ``lr_of_step(t)`` evaluates the schedule on host — its value feeds the
    jitted step as a traced scalar each iteration."""
    kind = _SUPPORTED_CLASSES.get(type(optimizer).__name__)
    if kind is None:
        return None

    def mult_by_name(mult):
        out = {}
        for key, val in (mult or {}).items():
            name = optimizer.idx2name.get(key, key) if isinstance(key, int) else key
            if name in param_names:
                out[str(name)] = float(val)
        return out

    kwargs = dict(
        learning_rate=optimizer.lr,
        wd=getattr(optimizer, "wd", 0.0),
        rescale_grad=getattr(optimizer, "rescale_grad", 1.0),
        clip_gradient=getattr(optimizer, "clip_gradient", None),
        lr_mult=mult_by_name(optimizer.lr_mult),
        wd_mult=mult_by_name(optimizer.wd_mult),
    )
    if kind in ("sgd", "nag"):
        kwargs["momentum"] = getattr(optimizer, "momentum", 0.0)
    if kind == "adam":
        kwargs.update(
            beta1=getattr(optimizer, "beta1", 0.9),
            beta2=getattr(optimizer, "beta2", 0.999),
            epsilon=getattr(optimizer, "epsilon", 1e-8),
        )
    init, apply = make_functional_optimizer(kind, **kwargs)

    def lr_of_step(t):
        if optimizer.lr_scheduler is not None:
            return float(optimizer.lr_scheduler(int(t)))
        return float(optimizer.lr)

    return init, apply, lr_of_step
