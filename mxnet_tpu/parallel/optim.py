"""Functional optimizers for the in-step SPMD update.

The imperative ``mxnet_tpu.optimizer`` classes update NDArrays key-by-key —
fine for the Module/KVStore path, but the SPMD trainer needs the update
*inside* the jitted step (the reference's ``update_on_kvstore`` moved the
optimizer onto ps-lite servers, kvstore_dist_server.h:164-198; SPMD moves it
into the compiled program). These return pure ``(init, apply)`` pairs over
parameter pytrees, mirroring the fused-op semantics of ops/optimizer_ops.py.
"""
from __future__ import annotations

__all__ = ["make_functional_optimizer"]


def make_functional_optimizer(name="sgd", learning_rate=0.01, wd=0.0,
                              rescale_grad=1.0, clip_gradient=None,
                              momentum=0.9, beta1=0.9, beta2=0.999,
                              epsilon=1e-8, **_ignored):
    """Return ``(init_fn, apply_fn)``.

    ``init_fn(params) -> state``; ``apply_fn(params, grads, state) ->
    (new_params, new_state)``. All pure jax, so the whole update fuses into
    the training step's XLA computation."""
    import jax
    import jax.numpy as jnp

    lr, mom = learning_rate, momentum

    def prep(g):
        g = g * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return g

    if name in ("sgd", "nag"):
        use_mom = mom > 0

        def init(params):
            t = jnp.zeros((), "int32")
            if not use_mom:
                return {"t": t}
            return {"t": t, "mom": jax.tree.map(jnp.zeros_like, params)}

        def apply(params, grads, state):
            def upd(w, g, m=None):
                g = prep(g) + wd * w
                if m is None:
                    return w - lr * g, None
                new_m = mom * m - lr * g
                if name == "nag":  # Nesterov lookahead (reference optimizer.py NAG)
                    return w + mom * new_m - lr * g, new_m
                return w + new_m, new_m

            if use_mom:
                out = jax.tree.map(lambda w, g, m: upd(w, g, m), params, grads, state["mom"])
                new_params = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
                new_mom = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
                return new_params, {"t": state["t"] + 1, "mom": new_mom}
            new_params = jax.tree.map(lambda w, g: upd(w, g)[0], params, grads)
            return new_params, {"t": state["t"] + 1}

        return init, apply

    if name == "adam":

        def init(params):
            return {
                "t": jnp.zeros((), "int32"),
                "m": jax.tree.map(jnp.zeros_like, params),
                "v": jax.tree.map(jnp.zeros_like, params),
            }

        def apply(params, grads, state):
            t = state["t"] + 1
            # bias-corrected step size, as the reference Adam computes lr_t
            lr_t = lr * jnp.sqrt(1.0 - beta2 ** t.astype("float32")) / (
                1.0 - beta1 ** t.astype("float32"))

            def upd(w, g, m, v):
                g = prep(g) + wd * w
                m = beta1 * m + (1 - beta1) * g
                v = beta2 * v + (1 - beta2) * g * g
                return w - lr_t * m / (jnp.sqrt(v) + epsilon), m, v

            out = jax.tree.map(upd, params, grads, state["m"], state["v"])
            first = lambda i: jax.tree.map(lambda o: o[i], out, is_leaf=lambda x: isinstance(x, tuple))
            return first(0), {"t": t, "m": first(1), "v": first(2)}

        return init, apply

    raise ValueError("unknown functional optimizer %r (have sgd/nag/adam)" % name)
