"""Module API: high-level training interface.

Counterpart of the reference's python/mxnet/module/ package (BaseModule
base_module.py:79, Module module.py:22, BucketingModule, SequentialModule).
"""
from .base_module import BaseModule, BatchEndParam
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule
from .elastic import ElasticFit
from .executor_group import PipelineExecutorGroup

__all__ = ["BaseModule", "BatchEndParam", "Module", "BucketingModule",
           "SequentialModule", "PythonModule", "PythonLossModule",
           "ElasticFit", "PipelineExecutorGroup"]
