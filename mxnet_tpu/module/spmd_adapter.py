"""Lower Module's forward_backward+update onto one jitted SPMD step.

The reference's hot path (SURVEY.md §3.1) runs per-device executors and then
a per-key KVStore push/pull; on a TPU mesh that becomes host-side reduction,
which can never feed the MFU target. When a ``Module`` spans more than one
device — or its kvstore is ``dist_tpu_sync`` across processes — this adapter
replaces the exec-group + kvstore loop with ``parallel.SPMDTrainer``:
forward + backward + gradient all-reduce + optimizer update compile into ONE
``jax.jit`` over the mesh, with XLA inserting the psum over ICI/DCN. The
Module API (``fit``/``forward_backward``/``update``/``get_outputs``/metrics/
checkpointing) is unchanged — only the execution strategy moves.

The legacy per-device path remains for: inference-only modules,
``inputs_need_grad``, fixed params, non-uniform work loads, custom grad_req,
and optimizers without a functional lowering. Bucketing rides the fused step
too: each bucket derives an adapter whose trainer shares the donor's state
cell (``derive``), giving one compiled step per bucket shape over one set of
live weights — the fused analogue of executor-per-bucket memory sharing.
"""
from __future__ import annotations

import logging
import os
import pickle

import numpy as np

__all__ = ["SPMDStepAdapter", "train_megastep_n"]


def train_megastep_n(default=1):
    """``MXNET_TRAIN_MEGASTEP_N``: batches buffered per fused dispatch.

    N=1 (the default) is today's one-dispatch-per-batch path. N>1 buffers N
    batches on the host and runs them through ONE ``lax.scan``-ed megastep
    (``SPMDTrainer.step_many``), amortizing the host dispatch seam the same
    way MXNET_DECODE_MEGASTEP_K does for serving. Junk or <1 falls back to
    ``default``."""
    raw = os.environ.get("MXNET_TRAIN_MEGASTEP_N", "")
    try:
        n = int(raw)
    except (TypeError, ValueError):
        return default
    return n if n >= 1 else default


class SPMDStepAdapter:
    def __init__(self, module, mesh, fn_opt, lr_of_step, shared=None,
                 rules=None):
        from ..parallel.trainer import SPMDTrainer

        self._lr_of_step = lr_of_step
        self._fn_opt = fn_opt
        self._data_names = list(module._data_names)
        self._label_names = list(module._label_names)
        self.trainer = SPMDTrainer(
            self._rewrite_symbol(module),
            mesh,
            data_names=tuple(self._data_names),
            label_names=tuple(self._label_names),
            optimizer=fn_opt,
            rules=rules,
        )
        self._optimizer = module._optimizer
        self._outputs = None
        self._pending_step = False  # a fused step ran, update() not yet seen
        self._megastep_n = train_megastep_n()
        self._buf = []           # buffered (data, label, lr, labels_nd) tuples
        self._metric_pairs = []  # flushed (labels_nd, outputs) awaiting metric
        if self._megastep_n > 1 and self.trainer._spans_processes:
            # step_many refuses multi-process meshes (a stacked global batch
            # cannot be assembled from process-local shards) — run N=1 rather
            # than fail on the first flush
            logging.warning(
                "MXNET_TRAIN_MEGASTEP_N=%d ignored: multi-process mesh — "
                "dispatching one batch per step", self._megastep_n)
            self._megastep_n = 1
        if self._megastep_n > 1 and shared is not None:
            # bucketing interleaves steps from several per-bucket adapters
            # over ONE shared state cell; buffering would flush them out of
            # order and corrupt the optimizer step sequence
            logging.warning(
                "MXNET_TRAIN_MEGASTEP_N=%d ignored for bucket adapter: "
                "shared-state buckets dispatch one batch per step",
                self._megastep_n)
            self._megastep_n = 1
        if shared is not None:
            # bucketing: same weights/opt state, a per-bucket compiled step —
            # this trainer shares `shared`'s state cell instead of re-adopting
            # host params (which would clobber live training state)
            self.trainer.adopt_state(shared.trainer)
        else:
            self.adopt_params(module._arg_params, module._aux_params)
        self._lint_plan(module)

    @staticmethod
    def _bind_hints(module):
        """The module's concrete bind shapes/dtypes — one derivation shared
        by the rewrite hook and the lint hook."""
        shapes, types = {}, {}
        for desc in list(module._data_shapes or []) + list(
                module._label_shapes or []):
            name, shape = desc[0], desc[1]
            shapes[name] = tuple(shape)
            dt = getattr(desc, "dtype", None)
            if dt is not None:
                types[name] = np.dtype(dt)
        return shapes, types

    def _rewrite_symbol(self, module):
        """MXNET_GRAPHREWRITE hook on the fused-step bind path: the SPMD
        trainer compiles the REWRITTEN graph (weight names are preserved by
        contract, so params/checkpoints/kvstore keys are unaffected). Same
        verify/fallback semantics as ``executor.bind``."""
        from ..analysis.rewrite import graphrewrite_mode, rewrite_for_bind

        if graphrewrite_mode() is None:
            return module._symbol
        shapes, types = self._bind_hints(module)
        return rewrite_for_bind(module._symbol, shapes, types,
                                grad_req="write", target="spmd_bind")[0]

    def _lint_plan(self, module):
        """MXNET_GRAPHLINT hook on the fused-step bind path. Unlike the
        single-device ``executor.bind`` lint, this one hands the passes the
        REAL mesh and sharding rules, so the GL4xx sharding-plan lint and
        the per-device GL5xx memory planner criticise the plan the trainer
        is about to compile."""
        from ..analysis import graphlint_mode, lint_bind

        mode = graphlint_mode()
        if mode is None:
            return
        shapes, types = self._bind_hints(module)
        lint_bind(self.trainer.symbol, shapes, types, mode,
                  target="spmd_bind", mesh=self.trainer.mesh,
                  rules=self.trainer.rules, train=True)

    @property
    def params_dirty(self):
        """Device state newer than host copies. Lives on the SHARED state
        cell: a step through bucket A must make bucket B's host view stale."""
        return self.trainer._state.dirty

    @params_dirty.setter
    def params_dirty(self, v):
        self.trainer._state.dirty = bool(v)

    def consume_pending_step(self):
        """True iff a fused step ran since the last update() — lets update()
        distinguish the fit() pairing from a manual fwd/bwd loop."""
        pending, self._pending_step = self._pending_step, False
        return pending

    # ------------------------------------------------------------------ params
    def adopt_params(self, arg_params, aux_params):
        """Take the module's host params as the trainer's state. In dist mode
        every worker adopts rank 0's values (the reference's kvstore-init
        broadcast, kvstore_dist.h Init)."""
        import jax

        arg = {k: np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
               for k, v in (arg_params or {}).items()}
        aux = {k: np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v)
               for k, v in (aux_params or {}).items()}
        if jax.process_count() > 1:
            from jax.experimental.multihost_utils import broadcast_one_to_all

            arg = {k: np.asarray(broadcast_one_to_all(v)) for k, v in arg.items()}
            aux = {k: np.asarray(broadcast_one_to_all(v)) for k, v in aux.items()}
        self.trainer.set_params(arg, aux)

    def export_params(self, arg_params, aux_params):
        """Write the trainer's current params back into the module's host
        NDArray dicts (checkpointing / get_params)."""
        self.flush()  # buffered megastep batches must land before export
        arg, aux = self.trainer.get_params()
        for k, v in arg.items():
            arg_params[k][:] = v
        for k, v in aux.items():
            aux_params[k][:] = v
        self.params_dirty = False

    # ------------------------------------------------------------------ step
    def step(self, data_batch):
        """The fused train step: fwd + bwd + all-reduce + update.

        With ``MXNET_TRAIN_MEGASTEP_N`` > 1 the batch is only BUFFERED here;
        every N-th call (or an explicit ``flush``) dispatches all N through
        one ``lax.scan``-ed megastep. The lr schedule is still read at
        buffer time, so schedules fire on the same optimizer step as the
        N=1 path."""

        def host(v):
            return v._jax() if hasattr(v, "_jax") else np.asarray(v)

        data = {n: host(v) for n, v in zip(self._data_names, data_batch.data)}
        label = {}
        if self._label_names and data_batch.label is not None:
            label = {n: host(v) for n, v in zip(self._label_names, data_batch.label)}
        opt = self._optimizer
        # legacy ordering (optimizer.py _update_count → _get_lr): the counter
        # increments BEFORE the schedule is read, so schedules fire on the
        # same step here as on the per-device path
        opt.num_update += 1
        lr = self._lr_of_step(opt.num_update)
        if self._megastep_n <= 1:
            self._outputs = self.trainer.step(data, label, lr=lr)
            self.params_dirty = True
            self._pending_step = True
            return
        # the iterator may reuse its buffers across next() calls — copy now
        data = {n: np.asarray(v) for n, v in data.items()}
        label = {n: np.asarray(v) for n, v in label.items()}
        labels_nd = list(data_batch.label) if data_batch.label is not None else []
        self._buf.append((data, label, lr, labels_nd))
        self._outputs = None
        self.params_dirty = True
        self._pending_step = True
        if len(self._buf) >= self._megastep_n:
            self.flush()

    def flush(self):
        """Dispatch any buffered batches through one N-step megastep."""
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        outs = self.trainer.step_many(
            [b[0] for b in buf], [b[1] for b in buf],
            lrs=[b[2] for b in buf])
        self._metric_pairs.extend(
            (b[3], o) for b, o in zip(buf, outs))
        self._outputs = outs[-1]

    def drain_metric(self, eval_metric):
        """Feed every flushed-but-unreported (labels, outputs) pair into
        ``eval_metric``. Returns True iff anything was drained."""
        from ..ndarray import NDArray

        pairs, self._metric_pairs = self._metric_pairs, []
        for labels_nd, outs in pairs:
            eval_metric.update(labels_nd, [NDArray(o) for o in outs])
        return bool(pairs)

    def update_metric(self, eval_metric, labels):
        """Module.update_metric seam. Returns True when this adapter owns
        the metric update (fused step ran), False → exec-group fallback.

        Megastep mode drains the flushed backlog instead of pairing the
        caller's ``labels`` with ``get_outputs()`` — with N batches per
        dispatch the latest outputs do not correspond to the current batch.
        A still-buffered batch also returns True (its metric row arrives at
        the next flush) so the exec group's stale forward is never used."""
        if self._megastep_n > 1:
            if self.drain_metric(eval_metric):
                return True
            return bool(self._buf)
        if self._outputs is None:
            return False
        eval_metric.update(labels, self.get_outputs())
        return True

    def get_outputs(self):
        """Step outputs as NDArrays. Multi-host: each process sees its own
        rows (the ones it fed), so update_metric(labels) pairs correctly."""
        import jax

        from ..ndarray import NDArray

        if self._outputs is None:
            return []
        outs = []
        for o in self._outputs:
            if self.trainer._spans_processes:
                from jax.experimental.multihost_utils import (
                    global_array_to_host_local_array,
                )

                o = global_array_to_host_local_array(
                    o, self.trainer.mesh,
                    self.trainer.rules.batch_spec(o.shape))
            outs.append(NDArray(o))
        return outs

    # ------------------------------------------------------------- opt states
    def get_states(self):
        import jax

        self.flush()  # buffered megastep batches must land before snapshot
        return pickle.dumps(jax.device_get(self.trainer.opt_state))

    def set_states(self, blob):
        import jax.numpy as jnp

        state = pickle.loads(blob)
        self.trainer.opt_state = _tree_jnp(state, jnp)


def _tree_jnp(x, jnp):
    if isinstance(x, dict):
        return {k: _tree_jnp(v, jnp) for k, v in x.items()}
    return jnp.asarray(x)


def try_create(module, kvstore_obj):
    """Create an adapter when the Module's configuration supports the fused
    SPMD step; otherwise return None (→ legacy per-device + kvstore path).

    Triggers: multi-device context, a ``dist*`` sync kvstore, or
    ``MXNET_MODULE_FUSED_STEP=1``. ``MXNET_MODULE_FUSED_STEP=0`` disables."""
    def rejected(why):
        # one findable log line naming the trigger — a user asking why their
        # pod runs the slow per-device path deserves the reason by name
        logging.warning("fused SPMD step disabled: %s — using the legacy "
                        "per-device + kvstore path", why)
        return None

    flag = os.environ.get("MXNET_MODULE_FUSED_STEP", "")
    if flag == "0":
        return None  # explicit opt-out, no warning needed
    dist = (kvstore_obj is not None and "dist" in kvstore_obj.type
            and "async" not in kvstore_obj.type)
    multi_dev = len(module._context) > 1
    if not (dist or multi_dev or flag == "1"):
        return None  # single device, nothing to fuse over — stay quiet
    if not module.for_training or module.inputs_need_grad:
        return None  # inference / grad-of-input binds are not a step at all
    if not getattr(module, "_fused_step_ok", True):
        return None  # explicit constructor opt-out (fused_step=False) — quiet
    if getattr(module, "_monitor_installed", False):
        return rejected("a Monitor is installed (per-op taps need the "
                        "exec-group path)")
    if module._fixed_param_names:
        return rejected("fixed_param_names is set")
    wl = module._work_load_list
    if wl and len(set(wl)) > 1:
        return rejected("uneven work_load_list %r" % (wl,))
    bad_req = [n for n in module._param_names
               if module._exec_group.grad_req.get(n) != "write"]
    if bad_req:
        return rejected("grad_req != 'write' for %s" % bad_req[:3])

    from ..parallel.optim import functional_from_optimizer

    fn = functional_from_optimizer(module._optimizer, set(module._param_names))
    if fn is None:
        logging.warning(
            "fused SPMD step unavailable for optimizer %s — falling back to "
            "the per-device kvstore path", type(module._optimizer).__name__)
        return None
    init, apply, lr_of_step = fn

    import jax

    from ..parallel.mesh import make_mesh

    if dist and jax.process_count() > 1:
        devices = list(jax.devices())  # global mesh: every process's chips
    else:
        try:
            devices = [ctx.jax_device for ctx in module._context]
        except Exception as exc:
            return rejected("context has no mappable jax device (%s)" % exc)
        if len({id(d) for d in devices}) != len(devices):
            return rejected("duplicate devices in context list")
    mesh, rules = None, None
    from ..parallel.autoplan import autoplan_enabled

    if autoplan_enabled():
        # MXNET_AUTOPLAN=1: the cost-model planner picks the mesh shape and
        # the per-param PartitionSpecs (docs/PARALLEL_PLANNER.md). Explicit
        # user specs always win — this path only runs for the adapter's own
        # default mesh; a caller constructing SPMDTrainer(rules=...) is
        # never overridden. Runs BEFORE the batch-divisibility guard: a
        # model-parallel plan (dp < devices) legitimately serves batches the
        # all-data mesh cannot split.
        mesh, rules = _autoplan_mesh(module, devices)
    if mesh is None:
        if module._exec_group.batch_size % len(module._context):
            return rejected(
                "batch size %d does not split evenly over %d devices"
                % (module._exec_group.batch_size, len(module._context)))
        mesh = make_mesh((len(devices),), ("data",), devices)
    else:
        # the planned mesh (single-process only — _autoplan_mesh rejects
        # dist) splits the batch over its data axis alone, so only dp must
        # divide the batch; a tp-heavy plan legitimately serves batch
        # sizes the all-data mesh cannot
        dp = dict(mesh.shape).get("data", 1)
        if module._exec_group.batch_size % dp:
            return rejected(
                "batch size %d does not split evenly over the planned "
                "data axis (dp=%d)"
                % (module._exec_group.batch_size, dp))
    return SPMDStepAdapter(module, mesh, (init, apply), lr_of_step,
                           rules=rules)


def _autoplan_mesh(module, devices):
    """Ask the auto-parallel planner for this module's mesh + sharding
    rules. Returns (None, None) — with a logged reason — on ANY failure or
    infeasibility: autoplan must never take down a job that would run fine
    on the default all-data mesh."""
    import jax

    from ..parallel import autoplan
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import ShardingRules

    if jax.process_count() > 1:
        # Unsupported for now, deliberately: the module's bind shapes are
        # per-process LOCAL batches while the mesh covers GLOBAL devices,
        # so the planner would price peaks/reshards at 1/P of reality —
        # and a tp-heavy winner with dp < P would glue DIFFERENT local
        # rows into one "replicated" global batch (silently wrong
        # gradients). Single-process meshes only until the planner is
        # taught global batch assembly.
        logging.warning(
            "MXNET_AUTOPLAN=1: multi-process (dist) jobs are not planned "
            "yet — using the default all-data mesh")
        return None, None

    shapes, types = {}, {}
    for desc in list(module._data_shapes or []) + list(
            module._label_shapes or []):
        name, shape = desc[0], desc[1]
        shapes[name] = tuple(shape)
        dt = getattr(desc, "dtype", None)
        if dt is not None:
            types[name] = np.dtype(dt)
    try:
        plan = autoplan.plan_parallel(module._symbol, shapes, types=types,
                                      devices=len(devices))
    except Exception as exc:
        # PlanError or anything the analysis passes throw on an exotic
        # graph: the documented contract is that autoplan NEVER takes down
        # a job that runs fine on the default mesh
        logging.warning("MXNET_AUTOPLAN=1: planner failed (%s: %s) — using "
                        "the default all-data mesh",
                        type(exc).__name__, exc)
        return None, None
    if not plan.feasible:
        logging.warning("MXNET_AUTOPLAN=1: no feasible plan (%s) — using "
                        "the default all-data mesh", plan.reason)
        return None, None
    if plan.pipeline_stages > 1:
        logging.warning(
            "MXNET_AUTOPLAN=1: the winning plan needs %d pipeline stages "
            "and the fused SPMD step cannot pipeline — train through "
            "module.PipelineExecutorGroup instead "
            "(docs/PARALLEL_PLANNER.md). Falling back to the default mesh.",
            plan.pipeline_stages)
        return None, None
    logging.info("MXNET_AUTOPLAN=1: %s", plan.summary())
    mesh = make_mesh(dict(plan.mesh), devices=devices)
    rules = ShardingRules(mesh, data_axis="data", model_axis="model",
                          param_rule=plan.param_rule())
    return mesh, rules


def derive(module, shared_adapter):
    """Adapter for a bucket Module that shares a bound module's training
    state (same weights/opt state, new compiled step for this bucket's
    shapes). Returns None — with one warning naming the trigger — when this
    bucket can't ride the fused step. The caller (borrow_optimizer) then
    RAISES rather than falling back: the donor trains on-device through the
    fused step, so a legacy per-bucket path would silently train against
    stale host weights."""
    if os.environ.get("MXNET_MODULE_FUSED_STEP", "") == "0":
        logging.warning("fused SPMD step disabled for bucket: "
                        "MXNET_MODULE_FUSED_STEP=0 set after the donor "
                        "module fused")
        return None
    if not module.for_training or module.inputs_need_grad:
        logging.warning("fused SPMD step disabled for bucket: module is "
                        "inference-only or needs input gradients")
        return None
    if module._exec_group.batch_size % len(module._context):
        logging.warning(
            "fused SPMD step disabled for bucket: batch size %d does not "
            "split evenly over %d devices", module._exec_group.batch_size,
            len(module._context))
        return None
    if shared_adapter._megastep_n > 1:
        # buckets interleave steps over the shared state cell; buffering on
        # the donor would flush out of order relative to bucket steps
        logging.warning(
            "MXNET_TRAIN_MEGASTEP_N=%d disabled: bucketing shares one "
            "optimizer state cell across modules — dispatching one batch "
            "per step from here on", shared_adapter._megastep_n)
        shared_adapter.flush()
        shared_adapter._megastep_n = 1
    try:
        # the donor's rules travel with its mesh: an autoplanned donor laid
        # params out per its plan, and the bucket trainer shares that state
        return SPMDStepAdapter(
            module, shared_adapter.trainer.mesh, shared_adapter._fn_opt,
            shared_adapter._lr_of_step, shared=shared_adapter,
            rules=shared_adapter.trainer.rules)
    except Exception as exc:
        logging.warning("fused SPMD step disabled for bucket: %s", exc)
        return None
