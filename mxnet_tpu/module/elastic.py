"""Elastic fault-tolerant training controller (docs/FAULT_TOLERANCE.md).

``Module.fit(elastic=...)`` routes here. ``ElasticFit`` wraps the classic
bind → init_params → init_optimizer → per-batch loop with the three things
the reference's ps-lite deployment had and the SPMD port lacked:

1. **Periodic asynchronous checkpointing** off the step path: in sharded
   update mode (``MXNET_KVSTORE_UPDATE=sharded``) each worker hands its 1/W
   flat optimizer shard to ``mxnet_tpu.checkpoint.Checkpointer``'s writer
   thread (device refs snapshot instantly; the device→host transfer and
   disk I/O overlap the next steps — ``checkpoint.inflight`` > 0 while
   they do); replicated mode snapshots weights+state pickle on rank 0.

2. **The pause protocol**: worker death becomes a *pause decision* in the
   coordination KV (``dist.propose_pause``; first-write-wins) naming the
   dead set and an agreed ``pause_at`` round. Every worker — proposers
   included — trains through exactly that round, so the collective count
   stays identical across workers. Two proposers exist: a SIGTERM'd worker
   draining itself (cleanest: no staleness wait), and the coordinator's
   per-round heartbeat scan (crashes).

3. **Re-form + resume**: at the pause round survivors drain in-flight
   buckets, snapshot or reach for the newest complete checkpoint, rebuild
   the collective layer over W−1 (``dist.reform`` → ``KVStore.reform``;
   the bucket-plan digest allgather re-verifies the new plan), rescale the
   gradient normalization for the new world size, reseed weights and flat
   optimizer shards, fast-forward the data iterator, and keep training.
   Workers named dead exit cleanly through ``EvictedError``.

What is NOT survivable (structured ``MXNetError``): the coordinator's own
death (its process hosts the coordination service), dropping below
``MXNET_ELASTIC_MIN_WORKERS``, and a crash (non-drain) death with no
complete checkpoint to reseed from — see docs/FAULT_TOLERANCE.md.
"""
from __future__ import annotations

import logging
import os
import signal
import time

import numpy as np

from .. import metric as metric_mod
from .. import telemetry as _tm
from ..base import EvictedError, MXNetError

__all__ = ["ElasticFit"]


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return int(default)


class ElasticFit:
    """Elastic training loop for one Module (see module docstring).

    Parameters
    ----------
    module : Module
        Must run the per-key kvstore path (``fused_step=False``) when
        distributed — the fused SPMD step cannot re-form its mesh yet.
    checkpoint_dir : str, optional
        Sharded-checkpoint root; default ``MXNET_CHECKPOINT_DIR``. Without
        one, recovery falls back to the pause-time all-gather snapshot —
        which only a DRAINING departure can provide; a crash then becomes
        unrecoverable.
    checkpoint_period : int, optional
        Rounds between async checkpoints; default
        ``MXNET_CHECKPOINT_STEPS`` (25). 0 disables periodic checkpoints.
    check_interval : int, optional
        Rounds between the coordinator's heartbeat scans (default 1).
    resume : bool
        Load the newest complete checkpoint under ``checkpoint_dir`` at
        fit start (any world size) and fast-forward the iterator to its
        recorded position. Default True when a checkpoint exists.
    reseed : str
        Where a re-form reseeds state from: ``"auto"`` (default) prefers
        the pause-time all-gather snapshot on a clean drain — no rollback
        — and the newest complete checkpoint otherwise; ``"checkpoint"``
        always reseeds from the checkpoint (deterministic rollback — what
        the chaos parity test pins). Must be identical on every worker.
    """

    def __init__(self, module, checkpoint_dir=None, checkpoint_period=None,
                 check_interval=1, resume=True, reseed="auto", logger=None):
        from .. import checkpoint as ckpt

        self._mod = module
        self.logger = logger or getattr(module, "logger", logging)
        self.checkpoint_dir = checkpoint_dir or ckpt.checkpoint_dir()
        self.checkpoint_period = (
            _env_int("MXNET_CHECKPOINT_STEPS", 25)
            if checkpoint_period is None else int(checkpoint_period))
        self.check_interval = max(1, int(check_interval))
        self.resume = resume
        if reseed not in ("auto", "checkpoint"):
            raise MXNetError("elastic reseed must be 'auto' or "
                             "'checkpoint', got %r" % (reseed,))
        self.reseed = reseed
        self.evicted = False
        self._writer = None
        self._drain = False
        self._pending_pause = None
        self._resuming = False
        self._round = 0          # update rounds since step 0, ALL generations
        self._old_sigterm = None
        # recovery → loop directives
        self._resume_epoch = None
        self._resume_nbatch = None

    # ------------------------------------------------------------ properties
    @property
    def kv(self):
        return self._mod._kvstore

    def _dist(self):
        from .. import dist

        return dist

    def _elastic_dist(self):
        """True when the pause/re-form protocol is live: an elastic dist
        job spanning >1 process."""
        dist = self._dist()
        kv = self.kv
        return (kv is not None and "dist" in kv.type
                and dist.elastic_enabled() and kv.num_workers > 1)

    # -------------------------------------------------------------- lifecycle
    def _install_sigterm(self):
        def _on_term(signum, frame):
            self._drain = True

        try:
            self._old_sigterm = signal.signal(signal.SIGTERM, _on_term)
        except ValueError:  # not the main thread
            self._old_sigterm = None

    def _restore_sigterm(self):
        if self._old_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._old_sigterm)
            except ValueError:
                pass
            self._old_sigterm = None

    def _ensure_writer(self):
        from .. import checkpoint as ckpt

        if self._writer is None and self.checkpoint_dir:
            self._writer = ckpt.Checkpointer(self.checkpoint_dir)
        return self._writer

    # ------------------------------------------------------------------- fit
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="dist_tpu_sync", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None):
        """The elastic counterpart of ``BaseModule.fit`` (same contract;
        no ``monitor`` — per-op monitoring and re-forms don't mix)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform

        mod = self._mod
        if initializer is None:
            initializer = Uniform(0.01)
        mod.bind(data_shapes=train_data.provide_data,
                 label_shapes=train_data.provide_label,
                 for_training=True, force_rebind=force_rebind)
        mod.init_params(initializer=initializer, arg_params=arg_params,
                        aux_params=aux_params, allow_missing=allow_missing,
                        force_init=force_init)
        mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                           optimizer_params=optimizer_params,
                           force_init=force_init)
        if mod._spmd is not None and self._elastic_dist():
            raise MXNetError(
                "elastic training needs the per-key kvstore path: build the "
                "Module with fused_step=False (the fused SPMD step cannot "
                "re-form its mesh over a changed process set yet)")

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        resume_epoch, resume_nbatch = begin_epoch, 0
        if self.resume and self.checkpoint_dir:
            got = self._try_resume()
            if got is not None:
                resume_epoch, resume_nbatch = got

        self._install_sigterm()
        try:
            self._run_epochs(train_data, eval_data, eval_metric,
                             validation_metric, epoch_end_callback,
                             batch_end_callback, eval_end_callback,
                             eval_batch_end_callback, resume_epoch,
                             resume_nbatch, num_epoch)
        except EvictedError as e:
            # expected exit of a drained/written-off worker: finish cleanly
            # so launchers see rc=0 (the SURVIVORS carry the job)
            self.evicted = True
            self.logger.info("elastic: %s", e)
        finally:
            self._restore_sigterm()
            if self._writer is not None:
                try:
                    # drain AND stop the writer thread (close is
                    # restartable: a later fit on this controller just
                    # spins a fresh one)
                    self._writer.close()
                except MXNetError as e:
                    self.logger.warning("elastic: final checkpoint drain "
                                        "failed: %s", e)
        return self

    # ------------------------------------------------------------ main loop
    def _run_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, epoch_end_callback,
                    batch_end_callback, eval_end_callback,
                    eval_batch_end_callback, begin_epoch, begin_nbatch,
                    num_epoch):
        from .base_module import BatchEndParam, _as_list

        mod = self._mod
        epoch = begin_epoch
        resume_nbatch = begin_nbatch
        while epoch < num_epoch:
            tic = time.time()
            eval_metric.reset()
            restart = False
            for nbatch, data_batch in enumerate(train_data):
                if nbatch < resume_nbatch:
                    continue  # fast-forward to the resume point
                try:
                    mod.forward_backward(data_batch)
                    mod.update()
                    # update_metric stays under the guard: a dead peer's
                    # dispatch poison can surface at ANY device read,
                    # including the metric's output pull
                    mod.update_metric(eval_metric, data_batch.label)
                except EvictedError:
                    raise
                except Exception as exc:
                    # a CRASHED (non-draining) peer wedges or errors the
                    # round's collective long before its heartbeat goes
                    # stale — the round-boundary scan alone can never see
                    # it. Route the failure into the pause protocol;
                    # re-raises `exc` when no member actually died.
                    directive = self._recover_from_crash(exc, epoch, nbatch)
                    if directive == "recovered":
                        epoch = self._resume_epoch
                        resume_nbatch = self._resume_nbatch
                        restart = True
                        break
                    raise exc
                if _tm.enabled():
                    _tm.mark_step()
                self._round += 1
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(params)
                directive = self._on_round(epoch, nbatch)
                if directive == "recovered":
                    epoch = self._resume_epoch
                    resume_nbatch = self._resume_nbatch
                    restart = True
                    break
            if restart:
                train_data.reset()
                continue
            resume_nbatch = 0
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_params_, aux_params_ = mod.get_params()
            mod.set_params(arg_params_, aux_params_)
            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, mod.symbol, arg_params_, aux_params_)
            if eval_data:
                res = mod.score(eval_data, validation_metric,
                                score_end_callback=eval_end_callback,
                                batch_end_callback=eval_batch_end_callback,
                                epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()
            epoch += 1

    # ----------------------------------------------------------- round hook
    def _on_round(self, epoch, nbatch):
        """Everything elastic that happens at a round boundary: the
        periodic checkpoint, the drain/scan pause proposals, the poll, and
        — at the agreed round — the pause itself. Returns ``"recovered"``
        when a re-form happened and the loop must re-enter at the recorded
        resume point."""
        kv = self.kv
        if self._resuming:
            kv._set_elastic_state("running")
            self._resuming = False
        if self.checkpoint_period and self.checkpoint_dir \
                and self._round % self.checkpoint_period == 0:
            self._save_checkpoint(epoch, nbatch)
        if not self._elastic_dist():
            if self._drain:
                # drain outside the elastic protocol: best-effort
                # checkpoint, then stop — and say exactly what was saved
                saved = self._save_checkpoint(epoch, nbatch, block=True)
                if not self.checkpoint_dir:
                    raise EvictedError(
                        "SIGTERM drain at round %d with NO checkpoint_dir "
                        "configured — nothing was saved; stopping training"
                        % self._round)
                kv = self.kv
                if kv is not None and "dist" in kv.type \
                        and kv.num_workers > 1:
                    raise EvictedError(
                        "SIGTERM drain at round %d: local state written "
                        "under %r, but a NON-elastic multi-worker job "
                        "cannot commit a complete checkpoint from one rank "
                        "(the manifest is rank 0's) — launch with "
                        "--elastic / MXNET_ELASTIC=1 for survivable "
                        "drains; stopping training"
                        % (self._round, self.checkpoint_dir))
                if not saved:
                    raise EvictedError(
                        "SIGTERM drain at round %d: the final checkpoint "
                        "save FAILED (see warning above) — resume from the "
                        "previous complete step under %r; stopping "
                        "training" % (self._round, self.checkpoint_dir))
                raise EvictedError(
                    "SIGTERM drain: checkpoint written at round %d; "
                    "stopping training" % self._round)
            return None
        dist = self._dist()
        payload = self._pending_pause
        if payload is None:
            if self._drain:
                payload = dist.propose_pause([dist.orig_rank()], self._round)
                self.logger.info(
                    "elastic: SIGTERM — draining at round %d (pause_at %d)",
                    self._round, payload["pause_at"])
            elif dist.orig_rank() == 0 \
                    and self._round % self.check_interval == 0:
                # never name ourselves dead: this process is demonstrably
                # alive (it is running the scan) — a stale SELF file means
                # clock skew or a heartbeat-dir hiccup, not death
                dead = [d for d in dist.dead_members()
                        if d != dist.orig_rank()]
                if dead:
                    payload = dist.propose_pause(dead, self._round)
                    self.logger.warning(
                        "elastic: dead member(s) %s — pausing at round %d",
                        dead, payload["pause_at"])
            if payload is None:
                payload = dist.poll_pause()
            self._pending_pause = payload
        if payload is not None and self._round >= int(payload["pause_at"]):
            # the pause payload is first-write-wins in the coordination KV
            # and pause_at carries a full check_interval margin, so every
            # rank reads the SAME payload before reaching that round: the
            # branch is rank-uniform by protocol
            # graphlint: waive GL801 -- pause payload is rank-uniform (above)
            return self._execute_pause(payload, epoch, nbatch)
        return None

    # ------------------------------------------------------------ checkpoint
    def _save_checkpoint(self, epoch, nbatch, block=False):
        """Returns True when the save was submitted (and, for blocking
        saves, landed) — False when no writer is configured or it failed."""
        writer = self._ensure_writer()
        if writer is None:
            return False
        kv = self.kv
        meta = {"epoch": int(epoch), "nbatch": int(nbatch),
                "round": int(self._round)}
        eng = kv._bucket_engine if kv is not None else None
        try:
            if eng is not None and eng.mode == "sharded" \
                    and eng._sharded_state:
                extra = self._aux_extra() if self._rank() == 0 else None
                writer.save_sharded(kv, self._round, extra=extra, meta=meta,
                                    block=block)
            else:
                self._save_replicated(writer, meta, block=block)
            return True
        except MXNetError as e:
            # a failed checkpoint must not kill training — the NEXT save
            # re-raises through the writer's latch if the disk stays bad
            self.logger.warning("elastic: checkpoint at round %d failed: %s",
                                self._round, e)
            return False

    def _rank(self):
        return self.kv.rank if self.kv is not None else 0

    def _aux_extra(self):
        """Aux params (BN moving stats etc.) as rank-0 extra files — they
        never flow through the kvstore but a resume needs them."""
        _, aux = self._mod.get_params()
        return {"aux:%s" % k: v.asnumpy() for k, v in aux.items()} or None

    def _save_replicated(self, writer, meta, block=False):
        kv = self.kv
        mod = self._mod
        if self._rank() != 0:
            # rank 0 writes the full replicated weights; gathering a whole
            # device→host copy here only to have save_replicated discard
            # it would make every non-zero rank pay the snapshot for nothing
            return
        args, auxs = mod.get_params()
        weights = {"arg:%s" % k: v.asnumpy() for k, v in args.items()}
        weights.update({"aux:%s" % k: v.asnumpy() for k, v in auxs.items()})
        states = None
        if mod._spmd is not None:
            # fused SPMD step: the adapter owns the optimizer state (there
            # is no kv._updater on this path)
            states = mod._spmd.get_states()
        else:
            updater = kv._updater if kv is not None else mod._updater
            if updater is not None:
                states = updater.get_states()
        writer.save_replicated(
            self._round, weights, states_bytes=states, meta=meta,
            world=kv.num_workers if kv is not None else 1,
            rank=0, block=block)

    def _try_resume(self):
        """Load the newest complete checkpoint at fit start; returns the
        recorded ``(epoch, nbatch + 1)`` resume point or None."""
        from .. import checkpoint as ckpt

        got = ckpt.latest_complete(self.checkpoint_dir)
        if got is None:
            return None
        step, manifest = got
        self._seed_from_checkpoint(step, manifest)
        meta = manifest.get("meta", {})
        self._round = int(meta.get("round", step))
        epoch = int(meta.get("epoch", 0))
        nbatch = int(meta.get("nbatch", -1))
        self.logger.info(
            "elastic: resumed from checkpoint step %d (epoch %d, batch %d, "
            "saved by a %d-worker run)", step, epoch, nbatch,
            int(manifest.get("world", 0)))
        return epoch, nbatch + 1

    def _seed_from_checkpoint(self, step, manifest, rebind=False):
        """Weights + optimizer state from a checkpoint step into the
        kvstore, the module and the bound executors."""
        from .. import checkpoint as ckpt

        kv = self.kv
        mod = self._mod
        if manifest.get("kind") == "sharded":
            if kv is None:
                raise MXNetError(
                    "sharded checkpoint %d needs a kvstore-backed fit"
                    % step)
            _, weights = kv.load_sharded_checkpoint(self.checkpoint_dir,
                                                    step=step)
            names = mod._param_names
            args = {}
            for key, w in weights.items():
                name = names[key] if isinstance(key, int) \
                    and key < len(names) else key
                args[name] = w
            auxs = {k[len("aux:"):]: v for k, v in ckpt.read_extra(
                self.checkpoint_dir, step, manifest).items()
                if k.startswith("aux:")}
        else:
            d = ckpt.step_dir(self.checkpoint_dir, step)
            blob = ckpt._load_npz_checked(os.path.join(d, "weights.npz"))
            args = {k[len("arg:"):]: v for k, v in blob.items()
                    if k.startswith("arg:")}
            auxs = {k[len("aux:"):]: v for k, v in blob.items()
                    if k.startswith("aux:")}
            states_path = os.path.join(d, "states.bin")
            if os.path.exists(states_path):
                with open(states_path, "rb") as f:
                    blob = f.read()
                if mod._spmd is not None:
                    mod._spmd.set_states(blob)
                else:
                    updater = kv._updater if kv is not None \
                        else mod._updater
                    if updater is not None:
                        updater.set_states(blob)
                        if kv is not None and \
                                kv._bucket_engine is not None:
                            kv._bucket_engine.reseed_updater_states()
        self._adopt_params(args, auxs, rebind=rebind)

    def _adopt_params(self, args, auxs, rebind=False):
        """Write host weight arrays into the module's params + executors
        AND the kvstore's stored values (the pull source of truth).

        ``rebind=True`` (post-re-form): the bound executors and every
        parameter array still reference the TORN-DOWN backend — operations
        mixing them with the new backend's arrays are undefined. Drop the
        executor group wholesale and re-bind on the new backend, then seed
        the fresh arrays from the host copies."""
        from .. import ndarray as nd

        mod = self._mod
        args_nd = {k: nd.array(np.asarray(v)) for k, v in args.items()}
        auxs_nd = {k: nd.array(np.asarray(v)) for k, v in (auxs or {}).items()}
        if rebind:
            data_shapes = mod._data_shapes
            label_shapes = mod._label_shapes
            mod._reset_bind()
            mod._arg_params = None
            mod._aux_params = None
            mod.params_initialized = False
            mod.bind(data_shapes=data_shapes, label_shapes=label_shapes,
                     for_training=True)
            mod.init_params(initializer=None, arg_params=args_nd,
                            aux_params=auxs_nd, allow_missing=True,
                            force_init=True)
        else:
            mod.set_params(args_nd, auxs_nd, allow_missing=True,
                           force_init=True)
        kv = self.kv
        if kv is not None:
            names = mod._param_names
            for key in list(kv._store):
                name = names[key] if isinstance(key, int) \
                    and key < len(names) else key
                if name in args_nd:
                    kv._reseed(key, args_nd[name])

    # --------------------------------------------------------- crash path
    @staticmethod
    def _collective_suspect(exc):
        """Whether a step failure plausibly came from the collective
        fabric (a dead peer) rather than plain host-side code: the jax
        runtime's error types, or messages naming the transport. User-code
        bugs (metrics, callbacks) raise ordinary Python exceptions that
        match neither — those must surface immediately instead of paying
        the dead-member staleness wait on every worker."""
        if type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
        msg = str(exc)
        return any(t in msg for t in (
            "Gloo", "gloo", "collective", "Connection",
            "dispatching computation", "FAILED_PRECONDITION",
            "DataLoss", "UNKNOWN:"))

    def _recover_from_crash(self, exc, epoch, nbatch):
        """A step failed mid-collective on an elastic job. If a member is
        (or becomes) dead, run the pause/re-form immediately — the fabric
        is already broken, there is no round to train through — reseeding
        from the checkpoint only (the dead worker never drained, so no
        pause-time snapshot exists). Re-raises ``exc`` when no member
        death explains the failure within the staleness window."""
        if not self._elastic_dist():
            raise exc
        dist = self._dist()
        self.logger.warning(
            "elastic: step at round %d failed (%s: %s) — checking for "
            "dead members", self._round, type(exc).__name__, exc)
        payload = self._pending_pause or dist.poll_pause()
        if payload is None:
            dead = [d for d in dist.dead_members()
                    if d != dist.orig_rank()]
            if not dead and not self._collective_suspect(exc):
                # no dead member, no published pause, and the exception
                # does not look like a fabric failure: a host-side bug
                # (user metric/callback code) — surface it now rather
                # than stall every worker through the staleness window
                raise exc
            # a hard-killed peer's sockets close fast; its heartbeat file
            # decays slower — wait out the staleness window for the
            # evidence (another survivor may publish the pause first)
            deadline = time.time() + dist.dead_timeout_seconds() + 30.0
            while not dead and payload is None \
                    and time.time() < deadline:
                time.sleep(1.0)
                dead = [d for d in dist.dead_members()
                        if d != dist.orig_rank()]
                if not dead:
                    payload = dist.poll_pause()
            if payload is None:
                if not dead:
                    raise exc  # not a membership failure — let it surface
                payload = dist.propose_pause(dead, self._round)
        return self._execute_pause(payload, epoch, nbatch, crashed=True)

    # --------------------------------------------------------------- pause
    def _execute_pause(self, payload, epoch, nbatch, crashed=False):
        """The agreed pause round was reached: drain, snapshot-or-
        checkpoint, re-form over the survivors, reseed, resume (or exit
        through EvictedError when this worker is in the dead set)."""
        from .. import checkpoint as ckpt

        dist = self._dist()
        kv = self.kv
        t0 = time.time()
        kv._set_elastic_state("paused")
        self.logger.info("elastic: paused at round %d (payload %s%s)",
                         self._round, payload,
                         ", after collective failure" if crashed else "")
        if self._writer is not None:
            try:
                self._writer.wait()  # in-flight async shard writes must land
            except MXNetError as e:
                # a failed LAST write only moves the agreed reseed step to
                # an older complete checkpoint — it must not kill recovery
                self.logger.warning("elastic: checkpoint drain at pause "
                                    "failed: %s", e)
        eng = kv._bucket_engine
        if eng is not None and not crashed:
            eng.finalize_all()  # symmetric: every member drains in-flight
        # a DRAIN departure (the proposer named itself dead) leaves the full
        # membership alive at the pause round, so the all-gather snapshot is
        # available; a crash leaves only what reached the disk. The choice
        # is payload+config-determined — identical on every worker, which
        # the snapshot's collectivity requires. After a collective FAILURE
        # neither finalize nor the snapshot all-gather can run — the fabric
        # those collectives need is the thing that just broke.
        drain = (not crashed
                 and bool(payload.get("proposer") in payload.get("dead", ())))
        snapshot = self._snapshot_host() if drain else None
        evicted = None
        try:
            plan = dist.plan_from_pause(payload)
        except EvictedError as e:
            evicted = e
        if evicted is not None:
            dist.stop_heartbeat(remove=True)
            raise evicted
        with _tm.span("dist.recover", generation=payload["generation"]):
            dist.reform(plan)
            kv.reform()
            self._rescale(plan)
            step = self._agree_checkpoint_step(payload["generation"])
            use_ckpt = step is not None and \
                (self.reseed == "checkpoint" or snapshot is None)
            if use_ckpt:
                manifest = ckpt.load_manifest(self.checkpoint_dir, step)
                if manifest is None:
                    raise MXNetError(
                        "elastic recovery: agreed checkpoint step %d under "
                        "%r lost its manifest between agreement and load"
                        % (step, self.checkpoint_dir))
                self._seed_from_checkpoint(step, manifest, rebind=True)
                meta = manifest.get("meta", {})
                self._round = int(meta.get("round", step))
                self._resume_epoch = int(meta.get("epoch", epoch))
                self._resume_nbatch = int(meta.get("nbatch", nbatch)) + 1
            elif snapshot is not None:
                self._reseed_from_snapshot(snapshot)
                self._resume_epoch, self._resume_nbatch = epoch, nbatch + 1
            else:
                raise MXNetError(
                    "elastic recovery impossible: worker(s) %s died "
                    "without draining and no COMPLETE checkpoint exists "
                    "under %r — the dead workers' optimizer shards are "
                    "lost. Unrecoverable; restart the job"
                    % (payload.get("dead"), self.checkpoint_dir))
        kv._set_elastic_state("resuming")
        self._pending_pause = None
        self._resuming = True
        if _tm.enabled():
            _tm.counter("dist.recoveries").inc()
            _tm.event("dist.recovered", generation=payload["generation"],
                      world=plan["world"],
                      seconds=round(time.time() - t0, 3))
        self.logger.info(
            "elastic: re-formed generation %d over %d worker(s) in %.2fs — "
            "resuming at epoch %d batch %d (round %d)",
            payload["generation"], plan["world"], time.time() - t0,
            self._resume_epoch, self._resume_nbatch, self._round)
        return "recovered"

    def _agree_checkpoint_step(self, generation):
        """The survivors must reseed from the SAME checkpoint step, and a
        shared-filesystem scan can race a manifest landing — so the
        coordinator's answer is published once in the coordination KV and
        everyone else reads that. None = no complete checkpoint exists."""
        import json

        from .. import checkpoint as ckpt

        dist = self._dist()
        client = dist.coordination_client()
        key = "mxtpu-elastic/gen-%d/ckpt-step" % generation
        if dist.orig_rank() == 0:
            got = ckpt.latest_complete(self.checkpoint_dir) \
                if self.checkpoint_dir else None
            step = got[0] if got else -1
            try:
                client.key_value_set(key, json.dumps(step))
            except Exception:
                pass  # replayed recovery: first write stands
        try:
            step = int(json.loads(client.blocking_key_value_get(
                key, 60_000)))
        except Exception as e:
            raise MXNetError(
                "elastic recovery: the coordinator never published the "
                "checkpoint-step agreement for generation %d (%s)"
                % (generation, e)) from e
        return None if step < 0 else step

    def _snapshot_host(self):
        """Pause-time host snapshot: replicated weights + per-key optimizer
        states (all-gathered from the flat shards in sharded mode). Taken
        by EVERY member — the all-gather is a collective."""
        kv = self.kv
        eng = kv._bucket_engine
        weights = {key: v.asnumpy() for key, v in kv._store.items()}
        states = {}
        if eng is not None and eng.mode == "sharded" and eng._sharded_state:
            states = eng.export_per_key_states()
        elif kv._updater is not None:
            for key, st in kv._updater.states.items():
                if st is None:
                    continue
                tup = st if isinstance(st, (tuple, list)) else (st,)
                states[key] = [s.asnumpy() for s in tup]
        _, aux = self._mod.get_params()
        auxs = {k: v.asnumpy() for k, v in aux.items()}
        return {"weights": weights, "states": states, "aux": auxs}

    def _reseed_from_snapshot(self, snapshot):
        """Seed the re-formed store/engine from the pause-time snapshot:
        no rollback, training resumes exactly where it paused."""
        import jax.numpy as jnp

        from ..ndarray import NDArray

        kv = self.kv
        mod = self._mod
        names = mod._param_names
        args = {}
        for key, w in snapshot["weights"].items():
            name = names[key] if isinstance(key, int) and key < len(names) \
                else key
            args[name] = w
        self._adopt_params(args, snapshot["aux"], rebind=True)
        if kv._updater is not None:
            for key, arrs in snapshot["states"].items():
                nds = [NDArray(jnp.asarray(a)) for a in arrs]
                kv._updater.states[key] = \
                    nds[0] if len(nds) == 1 else tuple(nds)
            if kv._bucket_engine is not None:
                kv._bucket_engine.reseed_updater_states()

    def _rescale(self, plan):
        """The gradient normalization 1/(batch·W) must track the new world
        size — the re-formed engine re-traces its update kernels, folding
        the new constant in."""
        opt = self._mod._optimizer
        if opt is None:
            return
        old_world = plan["world"] + len(plan["dead"])
        opt.rescale_grad = opt.rescale_grad * old_world / plan["world"]
        self.logger.info("elastic: rescale_grad ×%d/%d → %g",
                         old_world, plan["world"], opt.rescale_grad)
