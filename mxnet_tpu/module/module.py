"""Module: symbol-backed training module.

Counterpart of the reference's python/mxnet/module/module.py:22. Binding
creates a DataParallelExecutorGroup (one fused-XLA executor per context);
``update()`` runs the optimizer through a KVStore (local/device/dist_tpu_sync)
or a local updater loop, mirroring model.py:99-116 _update_params.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import optimizer as opt
from .. import telemetry as _tm
from ..base import MXNetError, anomaly_guard_mode
from ..context import Context, current_context
from ..initializer import InitDesc, Uniform
from ..ndarray import zeros
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup


class Module(BaseModule):
    """(reference: module.py:22)"""

    def __init__(
        self,
        symbol,
        data_names=("data",),
        label_names=("softmax_label",),
        logger=logging,
        context=None,
        work_load_list=None,
        fixed_param_names=None,
        fused_step=True,
    ):
        super().__init__(logger=logger)
        # fused_step=False keeps the legacy per-device + kvstore execution
        # even when a mesh is available
        self._fused_step_ok = bool(fused_step)
        self._spmd = None
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._work_load_list = work_load_list

        self._symbol = symbol
        arg_names = symbol.list_arguments()
        self._data_names = list(data_names) if data_names else []
        self._label_names = list(label_names) if label_names else []
        for name in self._data_names:
            if name not in arg_names:
                raise MXNetError("data name %r not an argument of the symbol" % name)
        self._label_names = [n for n in self._label_names if n in arg_names]
        self._param_names = [
            n for n in arg_names if n not in self._data_names and n not in self._label_names
        ]
        self._aux_names = symbol.list_auxiliary_states()
        self._fixed_param_names = list(fixed_param_names or [])
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False

        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec_group = None
        self._preload_opt_states = None
        self._skipped_steps = 0  # anomaly-guard skips on the legacy path

    # ------------------------------------------------------------ properties
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.get_outputs()
        return list(zip(self._output_names, [o.shape for o in outs])) if outs else []

    # ---------------------------------------------------------------- params
    def get_params(self):
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def _sync_params_from_devices(self):
        if self._spmd is not None and self._spmd.params_dirty:
            self._spmd.export_params(self._arg_params, self._aux_params)
        else:
            self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def init_params(self, initializer=None, arg_params=None, aux_params=None, allow_missing=False, force_init=False):
        """(reference: module.py init_params)"""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        if self._arg_params is None:
            self._arg_params = {
                name: zeros(arr[0].shape, dtype=arr[0].dtype)
                for name, arr in zip(self._param_names, self._exec_group.param_arrays)
            }
        if self._aux_params is None:
            self._aux_params = {
                name: zeros(arr[0].shape, dtype=arr[0].dtype)
                for name, arr in zip(self._aux_names, self._exec_group.aux_arrays)
            }

        attrs = self._symbol.attr_dict()

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache_arr = cache[name]
                if cache_arr is not arr:
                    arr[:] = cache_arr
            else:
                if not allow_missing and cache is not None:
                    raise RuntimeError("%s is not presented" % name)
                if initializer is not None:
                    initializer(InitDesc(name, attrs.get(name, None)), arr)

        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)
        if self._spmd is not None:
            # params (re)loaded after the fused step was set up — the trainer
            # must adopt them or training would continue from stale weights
            self._spmd.adopt_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True):
        if not allow_missing:
            self.init_params(
                initializer=None,
                arg_params=arg_params,
                aux_params=aux_params,
                allow_missing=allow_missing,
                force_init=force_init,
            )
            return
        if self.params_initialized and not force_init:
            return
        self._exec_group.set_params(arg_params, aux_params)
        self._params_dirty = True
        self.params_initialized = True
        if self._spmd is not None:
            self._spmd.adopt_params(arg_params or {}, aux_params or {})

    # --------------------------------------------------------------- binding
    def bind(self, data_shapes, label_shapes=None, for_training=True, inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        """(reference: module.py bind)"""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already binded, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        if not for_training:
            assert not inputs_need_grad

        self._data_shapes = self._normalize_shapes(data_shapes)
        self._label_shapes = self._normalize_shapes(label_shapes) if label_shapes else None

        shared_group = None
        if shared_module is not None:
            assert isinstance(shared_module, Module) and shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol,
            self._context,
            self._work_load_list,
            self._data_shapes,
            self._label_shapes,
            self._param_names,
            for_training,
            inputs_need_grad,
            shared_group=shared_group,
            logger=self.logger,
            fixed_param_names=self._fixed_param_names,
            grad_req=grad_req,
        )
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            # force rebind after params exist: push them to the new executors
            self._exec_group.set_params(self._arg_params, self._aux_params)

    @staticmethod
    def _normalize_shapes(shapes):
        from ..io import DataDesc

        out = []
        for s in shapes:
            if isinstance(s, DataDesc):
                out.append(s)
            elif isinstance(s, tuple) and len(s) == 2:
                out.append(DataDesc(s[0], s[1]))
            else:
                out.append(DataDesc(s.name, s.shape, getattr(s, "dtype", np.float32)))
        return out

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None

    # -------------------------------------------------------------- optimizer
    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=(("learning_rate", 0.01),), force_init=False):
        """(reference: module.py:432 + model.py:40-77 _create_kvstore)"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return

        from ..kvstore_helper import create_kvstore

        kvstore_obj, update_on_kvstore = create_kvstore(
            kvstore, len(self._context), self._arg_params
        )

        batch_size = self._exec_group.batch_size
        if kvstore_obj and "dist" in kvstore_obj.type and "_sync" in kvstore_obj.type:
            batch_size *= kvstore_obj.num_workers
        rescale_grad = 1.0 / batch_size

        if isinstance(optimizer, str):
            idx2name = {}
            if update_on_kvstore:
                idx2name.update(enumerate(self._param_names))
            else:
                for k in range(len(self._context)):
                    idx2name.update(
                        {i * len(self._context) + k: n for i, n in enumerate(self._param_names)}
                    )
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol, param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore_obj
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        # TPU hot path: when the contexts form a mesh (or the kvstore is a
        # dist sync type), lower forward_backward+update onto ONE jitted
        # sharded step — no per-key host reduction (SURVEY §3.1 TPU mapping)
        from . import spmd_adapter

        self._spmd = spmd_adapter.try_create(self, kvstore_obj)
        if self._spmd is not None:
            self.logger.info(
                "Module: fused SPMD step active over %d device(s)%s",
                self._spmd.trainer.mesh.devices.size,
                " (multi-process)" if self._spmd.trainer._spans_processes else "",
            )
            self._update_on_kvstore = False
            self.optimizer_initialized = True
            if self._preload_opt_states is not None:
                self.load_optimizer_states(self._preload_opt_states)
                self._preload_opt_states = None
            return

        if kvstore_obj:
            # copy initialized params into the store; updates flow through it
            from ..kvstore_helper import initialize_kvstore

            initialize_kvstore(
                kvstore=kvstore_obj,
                param_arrays=self._exec_group.param_arrays,
                arg_params=self._arg_params,
                param_names=self._param_names,
                update_on_kvstore=update_on_kvstore,
            )
        if update_on_kvstore:
            kvstore_obj.set_optimizer(self._optimizer)
        else:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True

        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def borrow_optimizer(self, shared_module):
        """Share optimizer/updater with another module (reference:
        module.py borrow_optimizer, used by BucketingModule)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        if shared_module._spmd is not None:
            # bucketing over the fused SPMD step: this bucket gets its own
            # compiled step for its shapes, sharing the donor's live
            # weights/optimizer state (one state cell, N compiled steps)
            from . import spmd_adapter

            self._spmd = spmd_adapter.derive(self, shared_module._spmd)
            if self._spmd is None:
                raise MXNetError(
                    "bucket module cannot share the fused SPMD step (see "
                    "warning above); rebuild the BucketingModule with "
                    "fused_step=False or set MXNET_MODULE_FUSED_STEP=0")
            self._update_on_kvstore = False
        self.optimizer_initialized = True

    # ------------------------------------------------------------- train step
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if self._spmd is not None:
            # any batches still buffered for a training megastep must land
            # before we read params for a plain forward
            self._spmd.flush()
            if self._spmd.params_dirty:
                # SPMD steps update the trainer's params; refresh the bound
                # executors before a plain forward (score/predict after fit)
                self._sync_params_from_devices()
                self._exec_group.set_params(self._arg_params, self._aux_params)
            # this forward's outputs now own get_outputs/update_metric —
            # drop the stale fused-step outputs and any undrained train
            # metric pairs (they must not leak into a validation metric;
            # fit() drains them via flush_pending_steps before scoring)
            self._spmd._outputs = None
            self._spmd._metric_pairs = []
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Fused step — ONE XLA computation per device (or, in SPMD mode,
        one computation over the whole mesh including grad sync + update)."""
        assert self.binded and self.params_initialized
        if self._spmd is not None:
            self._params_dirty = True
            self._spmd.step(data_batch)
            return
        self._exec_group.forward_backward(data_batch)

    @property
    def skipped_steps(self):
        """Steps dropped by the NaN/Inf anomaly guard
        (``MXNET_ANOMALY_GUARD=skip``, docs/RESILIENCE.md) — fused-SPMD
        skips live on the trainer, legacy-path skips here."""
        if self._spmd is not None:
            return self._spmd.trainer.skipped_steps
        return self._skipped_steps

    def _first_nonfinite_grad(self):
        """The first param (symbol order) with a NaN/Inf gradient on any
        device, or None. Host-side check — the legacy per-device path's
        gradients already live as materialized per-device buffers, so this
        costs one device→host read per grad (opt-in via
        MXNET_ANOMALY_GUARD; the fused-SPMD path checks on device)."""
        for name, grads in zip(self._param_names, self._exec_group.grad_arrays):
            for g in grads:
                if g is None:
                    continue
                if not np.isfinite(g.asnumpy()).all():
                    return name
        return None

    def update(self):
        """(reference: module.py update → model.py _update_params[_on_kvstore])"""
        assert self.binded and self.params_initialized and self.optimizer_initialized
        if self._spmd is not None:
            if not self._spmd.consume_pending_step():
                # manual forward()/backward() ran through the exec_group —
                # the fused step never fired, so silently returning would
                # train nothing. Fail loudly instead of no-opping.
                raise MXNetError(
                    "update() without forward_backward() in fused-SPMD mode: "
                    "use forward_backward(), or build the Module with "
                    "fused_step=False (or MXNET_MODULE_FUSED_STEP=0) for the "
                    "manual forward/backward/update loop")
            return  # the optimizer already ran inside the fused step
        guard = anomaly_guard_mode()
        if guard is not None and self._kvstore is not None \
                and "dist" in self._kvstore.type:
            # a rank-LOCAL skip/raise would desynchronize the gradient
            # collective (peers enter the push this worker skips). The
            # fused-SPMD path decides inside one SPMD program, so every
            # rank agrees — that is the supported dist configuration.
            if not getattr(self, "_warned_guard_dist", False):
                self._warned_guard_dist = True
                self.logger.warning(
                    "MXNET_ANOMALY_GUARD is ignored on the legacy "
                    "per-device path with a dist kvstore: a rank-local "
                    "skip would desync the collective. Use the fused SPMD "
                    "step (the default for dist) for a guarded dist run.")
            guard = None
        if guard is not None:
            bad = self._first_nonfinite_grad()
            if bad is not None:
                # grad_req='add' ACCUMULATES across steps: leaving NaN in
                # those buffers would make every later step non-finite too
                # (NaN + x = NaN) — zero them so the dropped/raised step
                # doesn't poison the rest of the run
                for name, grads in zip(self._param_names,
                                       self._exec_group.grad_arrays):
                    if self._exec_group.grad_req.get(name) == "add":
                        for g in grads:
                            if g is not None:
                                g[:] = 0
                if guard == "raise":
                    raise MXNetError(
                        "anomaly guard: non-finite (NaN/Inf) gradient for "
                        "parameter %r — step NOT applied "
                        "(MXNET_ANOMALY_GUARD=raise)" % bad)
                self._skipped_steps += 1
                if _tm.enabled():
                    _tm.counter("trainer.skipped_steps").inc()
                self.logger.warning(
                    "anomaly guard: dropping this update — non-finite "
                    "gradient, first offending key %r (%d step(s) skipped "
                    "so far)", bad, self._skipped_steps)
                return
        self._params_dirty = True
        if self._update_on_kvstore:
            from ..kvstore_helper import update_params_on_kvstore

            sparse_idx = getattr(self, "_sparse_grad_idx", None)
            if sparse_idx is None:
                # params whose producer declared a row-sparse gradient
                # (SparseEmbedding / Embedding(sparse_grad=True)): their
                # pushes ride the KVStore sparse round + lazy update
                # (docs/SPARSE.md)
                from ..sparse import sparse_param_names

                names = set(sparse_param_names(self._symbol))
                sparse_idx = frozenset(
                    i for i, n in enumerate(self._param_names) if n in names)
                self._sparse_grad_idx = sparse_idx
            update_params_on_kvstore(
                self._exec_group.param_arrays, self._exec_group.grad_arrays,
                self._kvstore,
                priorities=self._exec_group.param_priorities,
                sparse_indices=sparse_idx,
            )
        else:
            from ..kvstore_helper import update_params

            update_params(
                self._exec_group.param_arrays,
                self._exec_group.grad_arrays,
                updater=self._updater,
                num_device=len(self._context),
                kvstore=self._kvstore,
                priorities=self._exec_group.param_priorities,
            )

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        if self._spmd is not None and self._spmd._outputs is not None:
            outs = self._spmd.get_outputs()
            return outs if merge_multi_context else [[o] for o in outs]
        return self._exec_group.get_outputs(merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        if self._spmd is not None and self._spmd.update_metric(eval_metric, labels):
            return
        self._exec_group.update_metric(eval_metric, labels)

    def flush_pending_steps(self, eval_metric=None):
        """Dispatch batches still buffered for a training megastep
        (``MXNET_TRAIN_MEGASTEP_N`` > 1) and, when ``eval_metric`` is given,
        drain their metric rows. fit() calls this at each epoch tail so a
        partial final buffer still trains and still scores."""
        if self._spmd is None or self._spmd._megastep_n <= 1:
            return
        self._spmd.flush()
        if eval_metric is not None:
            self._spmd.drain_metric(eval_metric)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor_installed = True
        if self._spmd is not None:
            self.logger.warning(
                "Monitor stats are not collected by the fused SPMD step; "
                "build the Module with fused_step=False to monitor per-op "
                "outputs")
        self._exec_group.install_monitor(mon)

    # ----------------------------------------------------------- persistence
    def save_optimizer_states(self, fname):
        """Atomic (temp + ``os.replace``) everywhere; the kvstore path
        routes through ``KVStore.save_optimizer_states`` so the sharded
        update's 1/W flat shards checkpoint too (a pointer file +
        digest-guarded shard set, docs/FAULT_TOLERANCE.md)."""
        assert self.optimizer_initialized
        from ..checkpoint import atomic_write_bytes

        if self._spmd is not None:
            atomic_write_bytes(fname, self._spmd.get_states())
        elif self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            atomic_write_bytes(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        """Inverse of ``save_optimizer_states``; a torn/corrupt file raises
        a structured ``MXNetError`` naming ``fname``."""
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._spmd is None:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            states = f.read()
        try:
            if self._spmd is not None:
                self._spmd.set_states(states)
            else:
                self._updater.set_states(states)
        except Exception as e:
            raise MXNetError(
                "optimizer-state file %r is torn or not a state pickle "
                "(%s: %s) — likely a crash mid-save; delete it and resume "
                "from the previous checkpoint"
                % (fname, type(e).__name__, e)) from e

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """(reference: module.py save_checkpoint)"""
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        self.logger.info('Saved checkpoint to "%s"', param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            self.logger.info('Saved optimizer state to "%s"', state_name)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """(reference: module.py:96)"""
        from ..model import load_checkpoint

        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod
