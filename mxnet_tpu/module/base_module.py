"""BaseModule: the fit/score/predict contract.

Counterpart of the reference's python/mxnet/module/base_module.py:79 — the
training loop (fit :368) is intact: bind → init_params → init_optimizer →
per-batch forward_backward/update/update_metric with epoch+batch callbacks.
The device-side step underneath is one fused XLA computation per batch.
"""
from __future__ import annotations

import logging
import time
from collections import namedtuple


from .. import metric as metric_mod
from .. import ndarray as nd
from .. import telemetry as _tm
from ..base import MXNetError

BatchEndParam = namedtuple("BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _as_list(obj):
    if obj is None:
        return []
    return obj if isinstance(obj, list) else [obj]


class BaseModule:
    """(reference: base_module.py:79)"""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # ------------------------------------------------------------ properties
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    # ------------------------------------------------------------- contract
    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=None, arg_params=None, aux_params=None, allow_missing=False, force_init=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False, force_init=True):
        self.init_params(
            initializer=None,
            arg_params=arg_params,
            aux_params=aux_params,
            allow_missing=allow_missing,
            force_init=force_init,
        )

    def bind(self, data_shapes, label_shapes=None, for_training=True, inputs_need_grad=False, force_rebind=False, shared_module=None, grad_req="write"):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd", optimizer_params=(("learning_rate", 0.01),), force_init=False):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def install_monitor(self, mon):
        raise NotImplementedError()

    # ----------------------------------------------------------- composites
    def forward_backward(self, data_batch):
        """(reference: base_module.py:191)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, batch_end_callback=None, score_end_callback=None, reset=True, epoch=0):
        """Evaluate on a data iterator (reference: base_module.py:196)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_params)
            actual_num_batch += 1
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch, eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """(reference: base_module.py:267)"""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0 : out.shape[0] - pad] for out in self.get_outputs()]
            yield (outputs, nbatch, eval_batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True, reset=True, always_output_list=False):
        """Forward over an iterator, concatenating outputs
        (reference: base_module.py:293)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0 : out.shape[0] - pad].copy() for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, "Cannot merge batches: different number of outputs."
            output_list2 = [
                nd.concatenate([out[i] for out in output_list]) for i in range(num_outputs)
            ]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(
        self,
        train_data,
        eval_data=None,
        eval_metric="acc",
        epoch_end_callback=None,
        batch_end_callback=None,
        kvstore="local",
        optimizer="sgd",
        optimizer_params=(("learning_rate", 0.01),),
        eval_end_callback=None,
        eval_batch_end_callback=None,
        initializer=None,
        arg_params=None,
        aux_params=None,
        allow_missing=False,
        force_rebind=False,
        force_init=False,
        begin_epoch=0,
        num_epoch=None,
        validation_metric=None,
        monitor=None,
        elastic=None,
    ):
        """Train over a data iterator (reference: base_module.py:368).

        ``elastic`` opts into fault-tolerant training
        (docs/FAULT_TOLERANCE.md): ``True`` or a dict of ``ElasticFit``
        knobs (``checkpoint_dir``, ``checkpoint_period``, ``reseed``, ...).
        The loop then checkpoints asynchronously off the step path and —
        on an elastic dist job (``MXNET_ELASTIC=1``) — survives worker
        death by pausing, re-forming the collective over the survivors and
        resuming. Returns the ``ElasticFit`` controller (check
        ``.evicted`` on it) instead of None."""
        assert num_epoch is not None, "please specify number of epochs"
        # explicit None/False test: elastic={} is a valid all-defaults knob
        # set and must not silently fall through to the classic loop
        if elastic is not None and elastic is not False:
            if monitor is not None:
                raise MXNetError(
                    "fit(elastic=) does not support monitor= — per-op "
                    "monitoring and collective re-forms don't mix")
            from .elastic import ElasticFit

            knobs = dict(elastic) if isinstance(elastic, dict) else {}
            return ElasticFit(self, **knobs).fit(
                train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=optimizer, optimizer_params=optimizer_params,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                initializer=initializer, arg_params=arg_params,
                aux_params=aux_params, allow_missing=allow_missing,
                force_rebind=force_rebind, force_init=force_init,
                begin_epoch=begin_epoch, num_epoch=num_epoch,
                validation_metric=validation_metric)
        from ..initializer import Uniform

        if initializer is None:
            initializer = Uniform(0.01)

        from ..io import DevicePrefetchIter, device_prefetch_enabled

        if (device_prefetch_enabled()
                and not isinstance(train_data, DevicePrefetchIter)):
            # double-buffered device-side prefetch (docs/PERF.md §15):
            # batch N+1's host slice + device transfer overlap step N
            self.logger.info(
                "Module.fit: MXNET_IO_DEVICE_PREFETCH=1 — wrapping the "
                "training iterator in DevicePrefetchIter")
            train_data = DevicePrefetchIter(train_data)

        self.bind(
            data_shapes=train_data.provide_data,
            label_shapes=train_data.provide_label,
            for_training=True,
            force_rebind=force_rebind,
        )
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(
            initializer=initializer,
            arg_params=arg_params,
            aux_params=aux_params,
            allow_missing=allow_missing,
            force_init=force_init,
        )
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer, optimizer_params=optimizer_params, force_init=force_init)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        warned_input_bound = False
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            # fetch time = what the step pipeline spends WAITING on input
            # (host slicing, queue stalls, blocking transfers) — the
            # io.input_bound_pct numerator. Timed here, at the consumer,
            # so every iterator composition is covered.
            fetch_s = 0.0
            nbatch = -1
            data_source = iter(train_data)
            while True:
                t_fetch = time.perf_counter()
                try:
                    data_batch = next(data_source)
                except StopIteration:
                    break
                fetch_s += time.perf_counter() - t_fetch
                nbatch += 1
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if _tm.enabled():
                    # close the step BEFORE the observers run: Monitor.toc
                    # and Speedometer read this step's registry row
                    _tm.mark_step()
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    batch_end_params = BatchEndParam(epoch=epoch, nbatch=nbatch, eval_metric=eval_metric, locals=locals())
                    for callback in _as_list(batch_end_callback):
                        callback(batch_end_params)

            # training megastep (MXNET_TRAIN_MEGASTEP_N>1): dispatch the
            # partial final buffer and drain its metric rows before the
            # epoch metric is logged or validation runs
            flush_pending = getattr(self, "flush_pending_steps", None)
            if flush_pending is not None:
                flush_pending(eval_metric)

            # input-bound fraction of this epoch's wall time
            # (docs/OBSERVABILITY.md io.input_bound_pct): visible without a
            # trace, warned once per fit past 10%
            epoch_wall = time.time() - tic
            if epoch_wall > 0 and nbatch >= 0:
                input_pct = 100.0 * fetch_s / epoch_wall
                if _tm.enabled():
                    _tm.gauge("io.input_bound_pct").set(round(input_pct, 2))
                if input_pct > 10.0 and not warned_input_bound:
                    warned_input_bound = True
                    self.logger.warning(
                        "input-bound: %.1f%% of epoch %d's wall time was "
                        "spent waiting on the data iterator "
                        "(io.input_bound_pct). Enable device-side prefetch "
                        "(MXNET_IO_DEVICE_PREFETCH=1 / io.DevicePrefetchIter"
                        ") or deepen the prefetch queue so input stops "
                        "gating the step.", input_pct, epoch)

            if getattr(eval_metric, "num_inst", 1):
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            else:
                # a Speedometer with auto_reset cleared the metric on the
                # epoch's last batch — logging 0/0 as 'nan' here would read
                # as divergence; the per-batch lines carry the real values
                self.logger.info(
                    "Epoch[%d] Train metric was reset by a batch callback on "
                    "the last batch; see the preceding Batch lines", epoch)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, time.time() - tic)

            arg_params_, aux_params_ = self.get_params()
            self.set_params(arg_params_, aux_params_)
            if epoch_end_callback is not None:
                for callback in _as_list(epoch_end_callback):
                    callback(epoch, self.symbol, arg_params_, aux_params_)

            if eval_data:
                res = self.score(
                    eval_data,
                    validation_metric,
                    score_end_callback=eval_end_callback,
                    batch_end_callback=eval_batch_end_callback,
                    epoch=epoch,
                )
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch, name, val)

            train_data.reset()

    # ----------------------------------------------------------- persistence
    def save_params(self, fname):
        """(reference: base_module.py:630). Atomic: temp + ``os.replace``
        — a crash mid-save leaves the previous file, never a torn one."""
        from ..checkpoint import atomic_replace

        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v.as_in_context(v.context) for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v.as_in_context(v.context) for k, v in aux_params.items()})
        with atomic_replace(fname) as tmp:
            nd.save(tmp, save_dict)

    def load_params(self, fname):
        """(reference: base_module.py:645). A torn/partial file raises a
        structured ``MXNetError`` naming ``fname``."""
        from ..checkpoint import load_ndarrays_checked

        save_dict = load_ndarrays_checked(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise ValueError("Invalid param file " + fname)
        self.set_params(arg_params, aux_params)
