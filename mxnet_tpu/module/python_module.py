"""Modules implemented in python, without a bound Symbol.

Counterpart of the reference's python/mxnet/module/python_module.py
(PythonModule :21, PythonLossModule :190): glue modules that sit in a
SequentialModule pipeline (or stand alone) for computation that should stay
on the host — custom losses, metric adapters, debugging taps. They have no
parameters and no compiled executable.
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..io import DataDesc
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """A parameterless module whose behavior is defined by overriding
    ``forward``/``backward`` in python (reference: python_module.py:21)."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # ------------------------------------------------------------ properties
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ------------------------------------------------------------ parameters
    def get_params(self):
        return ({}, {})

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False):
        self.params_initialized = True

    def update(self):
        pass

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    # ------------------------------------------------------------------ bind
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already binded, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._data_shapes = [
            d if isinstance(d, DataDesc) else DataDesc(*d) for d in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [
                l if isinstance(l, DataDesc) else DataDesc(*l) for l in label_shapes]
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        """Default: one output per output name, same shape as the data
        (override for anything else)."""
        return [DataDesc(name, self._data_shapes[0].shape)
                for name in self._output_names]

    # --------------------------------------------------------------- compute
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        pass

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """A pluggable python loss: forward caches the prediction, backward
    produces the input gradient via ``grad_func(scores, labels)``
    (reference: python_module.py:190 PythonLossModule)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"], logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(np.asarray(grad))
            self._scores_grad = grad
        else:
            raise MXNetError("PythonLossModule requires grad_func for backward")

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]
