"""DataParallelExecutorGroup: per-device executor management.

Counterpart of the reference's python/mxnet/module/executor_group.py:77
(decide_slices :207, bind_exec :270, forward :355, backward :481,
update_metric :511). One executor per context shares a single traced
_GraphProgram, so XLA compiles the step once per shape and dispatches it on
each device; gradient reduction across devices happens in the KVStore layer
(or the local updater path), as in the reference. The single-device case —
the common one on TPU, where *mesh* parallelism supersedes device lists
(see parallel/) — has zero slicing overhead.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..executor import _GraphProgram, simple_bind
from .. import ndarray as nd
from ..ndarray import NDArray, zeros


def _split_input_slice(batch_size, work_load_list):
    """Batch index ranges per device (reference: executor_group.py:207
    decide_slices / mxnet.executor_manager._split_input_slice)."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size must be >= number of devices")
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            stop = batch_size
        else:
            stop = start + int(round(batch_size * w / total))
        slices.append(slice(start, stop))
        start = stop
    return slices


class DataParallelExecutorGroup:
    """(reference: executor_group.py:77)"""

    def __init__(
        self,
        symbol,
        contexts: List[Context],
        workload,
        data_shapes,
        label_shapes,
        param_names,
        for_training,
        inputs_need_grad,
        shared_group=None,
        logger=None,
        fixed_param_names=None,
        grad_req="write",
    ):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.data_shapes = list(data_shapes)
        self.label_shapes = list(label_shapes) if label_shapes else None
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [d.name if hasattr(d, "name") else d[0] for d in self.data_shapes]
        self.label_names = (
            [l.name if hasattr(l, "name") else l[0] for l in self.label_shapes]
            if self.label_shapes
            else []
        )

        batch_axis = 0
        self.batch_size = (self.data_shapes[0].shape if hasattr(self.data_shapes[0], "shape") else self.data_shapes[0][1])[batch_axis]
        self.slices = _split_input_slice(self.batch_size, self.workload)

        # per-arg grad_req (params fixed → null; data per inputs_need_grad)
        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = (
                    "null" if (not for_training or name in self.fixed_param_names) else grad_req
                )
            elif name in self.data_names:
                self.grad_req[name] = grad_req if inputs_need_grad else "null"
            else:  # labels
                self.grad_req[name] = "null"

        # per-param comm priority for the dist KVStore's bucketed push/pull
        # (reference: executor_group.py's priority=-index transfer schedule):
        # derived from the symbol's topo order — shallower params (consumed
        # earlier in forward) get higher priority, so their pulls complete
        # first and the next forward can start while deep buckets are still
        # in flight. Keyed by kvstore key (= param index).
        self.param_priorities = self._topo_priorities(symbol)

        self.execs = []
        self._bind_execs(shared_group)

        # param_arrays[i] = list over devices of the NDArray for param i
        self.param_arrays = [
            [e.arg_dict[name] for e in self.execs] for name in self.param_names
        ]
        self.grad_arrays = [
            [e.grad_dict[name] for e in self.execs] for name in self.param_names
        ]
        self.aux_arrays = [[e.aux_dict[name] for e in self.execs] for name in self.aux_names]
        self.data_arrays = [[e.arg_dict[name] for e in self.execs] for name in self.data_names]
        self.label_arrays = [[e.arg_dict[name] for e in self.execs] for name in self.label_names]
        self.input_grad_arrays = (
            [[e.grad_dict[name] for e in self.execs] for name in self.data_names]
            if inputs_need_grad
            else []
        )

    def _topo_priorities(self, symbol):
        """{param index: priority} from the symbol DAG's topological order
        (reverse-topo emission is the caller's job; see
        kvstore_helper.update_params_on_kvstore)."""
        try:
            topo_vars = [n.name for n in symbol._topo() if n.is_variable]
        except Exception:  # foreign symbol object (tests): fall back to
            topo_vars = []  # argument order, which is topo by construction
        pos = {n: i for i, n in enumerate(topo_vars)}
        ranked = sorted(range(len(self.param_names)),
                        key=lambda i: pos.get(self.param_names[i], i))
        return {idx: -rank for rank, idx in enumerate(ranked)}

    def _bind_execs(self, shared_group):
        name2shape = {}
        for d in self.data_shapes:
            name2shape[d.name if hasattr(d, "name") else d[0]] = tuple(
                d.shape if hasattr(d, "shape") else d[1]
            )
        for l in self.label_shapes or []:
            name2shape[l.name if hasattr(l, "name") else l[0]] = tuple(
                l.shape if hasattr(l, "shape") else l[1]
            )
        for i, (ctx, slc) in enumerate(zip(self.contexts, self.slices)):
            dev_shapes = {}
            for name, shape in name2shape.items():
                n = slc.stop - slc.start
                dev_shapes[name] = (n,) + shape[1:]
            shared = None
            if i > 0:
                shared = _SharedProgramCarrier(self.execs[0]._prog, self.symbol)
            if shared_group is None:
                ex = simple_bind(
                    self.symbol, ctx, grad_req=self.grad_req, shared_exec=shared, **dev_shapes
                )
            else:
                # bucketing path: every bucket's executor binds the SAME
                # parameter/grad/aux NDArrays as the shared (default-bucket)
                # module, so an update through any bucket updates all — the
                # reference's shared_exec memory sharing made literal
                # (graph_executor.cc:348-351)
                ex = self._bind_shared(shared_group, i, ctx, dev_shapes)
            self.execs.append(ex)

    def _bind_shared(self, shared_group, dev_i, ctx, dev_shapes):
        from ..executor import bind as _bind

        shared_ex = shared_group.execs[dev_i]
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**dev_shapes)
        if arg_shapes is None:
            raise MXNetError("bind (shared): insufficient shape info")
        args, grads, reqs = [], [], []
        for name, shape in zip(self.arg_names, arg_shapes):
            req = self.grad_req[name]
            if name in shared_ex.arg_dict and tuple(shared_ex.arg_dict[name].shape) == tuple(shape):
                args.append(shared_ex.arg_dict[name])
                grads.append(shared_ex.grad_dict.get(name) if req != "null" else None)
            else:
                args.append(zeros(shape, ctx=ctx))
                grads.append(zeros(shape, ctx=ctx) if req != "null" else None)
            reqs.append(req if grads[-1] is not None else "null")
        auxs = []
        for name, shape in zip(self.aux_names, aux_shapes):
            if name in shared_ex.aux_dict and tuple(shared_ex.aux_dict[name].shape) == tuple(shape):
                auxs.append(shared_ex.aux_dict[name])
            else:
                auxs.append(zeros(shape, ctx=ctx))
        return _bind(self.symbol, ctx, args, args_grad=grads, grad_req=reqs, aux_states=auxs)

    # -------------------------------------------------------------- dataflow
    def _load_slices(self, arrays_per_name, batch_arrays):
        """Copy sliced batch rows into each device's bound array
        (reference: executor_group.py _load_data/_load_general)."""
        if batch_arrays is None or len(batch_arrays) == 0:
            # label-less predict batch: nothing to load
            return
        if len(batch_arrays) < len(arrays_per_name):
            raise MXNetError(
                "batch supplies %d arrays but %d are bound — an iterator is "
                "under-feeding the module's inputs"
                % (len(batch_arrays), len(arrays_per_name)))
        for src, dev_arrays in zip(batch_arrays, arrays_per_name):
            src_np = None
            for dev_i, dst in enumerate(dev_arrays):
                slc = self.slices[dev_i]
                if len(self.contexts) == 1:
                    if isinstance(src, NDArray):
                        dst[:] = src
                    else:
                        dst[:] = np.asarray(src)
                else:
                    if src_np is None:
                        src_np = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
                    dst[:] = src_np[slc]

    def load_data_label(self, data_batch):
        self._load_slices(self.data_arrays, data_batch.data)
        if self.label_arrays and data_batch.label:
            self._load_slices(self.label_arrays, data_batch.label)

    def forward(self, data_batch, is_train=None):
        """(reference: executor_group.py:355)"""
        self.load_data_label(data_batch)
        if is_train is None:
            is_train = self.for_training
        for ex in self.execs:
            ex.forward(is_train=is_train)

    def backward(self, out_grads=None):
        """(reference: executor_group.py:481)"""
        assert self.for_training, "re-bind with for_training=True to run backward"
        for i, ex in enumerate(self.execs):
            if out_grads is None:
                ex.backward()
            else:
                dev_grads = []
                for g in out_grads:
                    if len(self.contexts) == 1:
                        dev_grads.append(g)
                    else:
                        dev_grads.append(g[self.slices[i].start : self.slices[i].stop])
                ex.backward(dev_grads)

    def forward_backward(self, data_batch):
        """Fused per-device fwd+bwd: one XLA computation per device per step."""
        self.load_data_label(data_batch)
        for ex in self.execs:
            ex.forward_backward()

    def get_outputs(self, merge_multi_context=True):
        outputs = [[ex.outputs[i] for ex in self.execs] for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return [outs[0] if len(outs) == 1 else nd.concatenate(outs, axis=0) for outs in outputs]
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        grads = [list(dev) for dev in self.input_grad_arrays]
        if merge_multi_context:
            return [g[0] if len(g) == 1 else nd.concatenate(g, axis=0) for g in grads]
        return grads

    def update_metric(self, eval_metric, labels):
        """(reference: executor_group.py:511)"""
        outputs = self.get_outputs(merge_multi_context=True)
        eval_metric.update(labels, outputs)

    # ---------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params, allow_extra_params=True)

    def get_params(self, arg_params, aux_params):
        """Copy device-0 values out (devices hold identical params)."""
        for i, name in enumerate(self.param_names):
            arg_params[name] = self.param_arrays[i][0].copy()
        for i, name in enumerate(self.aux_names):
            aux_params[name] = self.aux_arrays[i][0].copy()

    def install_monitor(self, mon):
        for ex in self.execs:
            mon.install(ex)


class _SharedProgramCarrier:
    """Minimal shared_exec stand-in carrying a _GraphProgram into bind()."""

    def __init__(self, prog, symbol):
        self._prog = prog
        self._symbol = symbol
