"""DataParallelExecutorGroup: per-device executor management.

Counterpart of the reference's python/mxnet/module/executor_group.py:77
(decide_slices :207, bind_exec :270, forward :355, backward :481,
update_metric :511). One executor per context shares a single traced
_GraphProgram, so XLA compiles the step once per shape and dispatches it on
each device; gradient reduction across devices happens in the KVStore layer
(or the local updater path), as in the reference. The single-device case —
the common one on TPU, where *mesh* parallelism supersedes device lists
(see parallel/) — has zero slicing overhead.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..executor import _GraphProgram, simple_bind
from .. import ndarray as nd
from ..ndarray import NDArray, zeros


def _split_input_slice(batch_size, work_load_list):
    """Batch index ranges per device (reference: executor_group.py:207
    decide_slices / mxnet.executor_manager._split_input_slice)."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise MXNetError("batch size must be >= number of devices")
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            stop = batch_size
        else:
            stop = start + int(round(batch_size * w / total))
        slices.append(slice(start, stop))
        start = stop
    return slices


class DataParallelExecutorGroup:
    """(reference: executor_group.py:77)"""

    def __init__(
        self,
        symbol,
        contexts: List[Context],
        workload,
        data_shapes,
        label_shapes,
        param_names,
        for_training,
        inputs_need_grad,
        shared_group=None,
        logger=None,
        fixed_param_names=None,
        grad_req="write",
    ):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.data_shapes = list(data_shapes)
        self.label_shapes = list(label_shapes) if label_shapes else None
        self.param_names = list(param_names)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])

        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = [d.name if hasattr(d, "name") else d[0] for d in self.data_shapes]
        self.label_names = (
            [l.name if hasattr(l, "name") else l[0] for l in self.label_shapes]
            if self.label_shapes
            else []
        )

        batch_axis = 0
        self.batch_size = (self.data_shapes[0].shape if hasattr(self.data_shapes[0], "shape") else self.data_shapes[0][1])[batch_axis]
        self.slices = _split_input_slice(self.batch_size, self.workload)

        # per-arg grad_req (params fixed → null; data per inputs_need_grad)
        self.grad_req = {}
        for name in self.arg_names:
            if name in self.param_names:
                self.grad_req[name] = (
                    "null" if (not for_training or name in self.fixed_param_names) else grad_req
                )
            elif name in self.data_names:
                self.grad_req[name] = grad_req if inputs_need_grad else "null"
            else:  # labels
                self.grad_req[name] = "null"

        # per-param comm priority for the dist KVStore's bucketed push/pull
        # (reference: executor_group.py's priority=-index transfer schedule):
        # derived from the symbol's topo order — shallower params (consumed
        # earlier in forward) get higher priority, so their pulls complete
        # first and the next forward can start while deep buckets are still
        # in flight. Keyed by kvstore key (= param index).
        self.param_priorities = self._topo_priorities(symbol)

        self.execs = []
        self._bind_execs(shared_group)

        # param_arrays[i] = list over devices of the NDArray for param i
        self.param_arrays = [
            [e.arg_dict[name] for e in self.execs] for name in self.param_names
        ]
        self.grad_arrays = [
            [e.grad_dict[name] for e in self.execs] for name in self.param_names
        ]
        self.aux_arrays = [[e.aux_dict[name] for e in self.execs] for name in self.aux_names]
        self.data_arrays = [[e.arg_dict[name] for e in self.execs] for name in self.data_names]
        self.label_arrays = [[e.arg_dict[name] for e in self.execs] for name in self.label_names]
        self.input_grad_arrays = (
            [[e.grad_dict[name] for e in self.execs] for name in self.data_names]
            if inputs_need_grad
            else []
        )

    def _topo_priorities(self, symbol):
        """{param index: priority} from the symbol DAG's topological order
        (reverse-topo emission is the caller's job; see
        kvstore_helper.update_params_on_kvstore)."""
        try:
            topo_vars = [n.name for n in symbol._topo() if n.is_variable]
        except Exception:  # foreign symbol object (tests): fall back to
            topo_vars = []  # argument order, which is topo by construction
        pos = {n: i for i, n in enumerate(topo_vars)}
        ranked = sorted(range(len(self.param_names)),
                        key=lambda i: pos.get(self.param_names[i], i))
        return {idx: -rank for rank, idx in enumerate(ranked)}

    def _bind_execs(self, shared_group):
        name2shape = {}
        for d in self.data_shapes:
            name2shape[d.name if hasattr(d, "name") else d[0]] = tuple(
                d.shape if hasattr(d, "shape") else d[1]
            )
        for l in self.label_shapes or []:
            name2shape[l.name if hasattr(l, "name") else l[0]] = tuple(
                l.shape if hasattr(l, "shape") else l[1]
            )
        for i, (ctx, slc) in enumerate(zip(self.contexts, self.slices)):
            dev_shapes = {}
            for name, shape in name2shape.items():
                n = slc.stop - slc.start
                dev_shapes[name] = (n,) + shape[1:]
            shared = None
            if i > 0:
                shared = _SharedProgramCarrier(self.execs[0]._prog, self.symbol)
            if shared_group is None:
                ex = simple_bind(
                    self.symbol, ctx, grad_req=self.grad_req, shared_exec=shared, **dev_shapes
                )
            else:
                # bucketing path: every bucket's executor binds the SAME
                # parameter/grad/aux NDArrays as the shared (default-bucket)
                # module, so an update through any bucket updates all — the
                # reference's shared_exec memory sharing made literal
                # (graph_executor.cc:348-351)
                ex = self._bind_shared(shared_group, i, ctx, dev_shapes)
            self.execs.append(ex)

    def _bind_shared(self, shared_group, dev_i, ctx, dev_shapes):
        from ..executor import bind as _bind

        shared_ex = shared_group.execs[dev_i]
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**dev_shapes)
        if arg_shapes is None:
            raise MXNetError("bind (shared): insufficient shape info")
        args, grads, reqs = [], [], []
        for name, shape in zip(self.arg_names, arg_shapes):
            req = self.grad_req[name]
            if name in shared_ex.arg_dict and tuple(shared_ex.arg_dict[name].shape) == tuple(shape):
                args.append(shared_ex.arg_dict[name])
                grads.append(shared_ex.grad_dict.get(name) if req != "null" else None)
            else:
                args.append(zeros(shape, ctx=ctx))
                grads.append(zeros(shape, ctx=ctx) if req != "null" else None)
            reqs.append(req if grads[-1] is not None else "null")
        auxs = []
        for name, shape in zip(self.aux_names, aux_shapes):
            if name in shared_ex.aux_dict and tuple(shared_ex.aux_dict[name].shape) == tuple(shape):
                auxs.append(shared_ex.aux_dict[name])
            else:
                auxs.append(zeros(shape, ctx=ctx))
        return _bind(self.symbol, ctx, args, args_grad=grads, grad_req=reqs, aux_states=auxs)

    # -------------------------------------------------------------- dataflow
    def _load_slices(self, arrays_per_name, batch_arrays):
        """Copy sliced batch rows into each device's bound array
        (reference: executor_group.py _load_data/_load_general)."""
        if batch_arrays is None or len(batch_arrays) == 0:
            # label-less predict batch: nothing to load
            return
        if len(batch_arrays) < len(arrays_per_name):
            raise MXNetError(
                "batch supplies %d arrays but %d are bound — an iterator is "
                "under-feeding the module's inputs"
                % (len(batch_arrays), len(arrays_per_name)))
        for src, dev_arrays in zip(batch_arrays, arrays_per_name):
            src_np = None
            for dev_i, dst in enumerate(dev_arrays):
                slc = self.slices[dev_i]
                if len(self.contexts) == 1:
                    if isinstance(src, NDArray):
                        dst[:] = src
                    else:
                        dst[:] = np.asarray(src)
                else:
                    if src_np is None:
                        src_np = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
                    dst[:] = src_np[slc]

    def load_data_label(self, data_batch):
        self._load_slices(self.data_arrays, data_batch.data)
        if self.label_arrays and data_batch.label:
            self._load_slices(self.label_arrays, data_batch.label)

    def forward(self, data_batch, is_train=None):
        """(reference: executor_group.py:355)"""
        self.load_data_label(data_batch)
        if is_train is None:
            is_train = self.for_training
        for ex in self.execs:
            ex.forward(is_train=is_train)

    def backward(self, out_grads=None):
        """(reference: executor_group.py:481)"""
        assert self.for_training, "re-bind with for_training=True to run backward"
        for i, ex in enumerate(self.execs):
            if out_grads is None:
                ex.backward()
            else:
                dev_grads = []
                for g in out_grads:
                    if len(self.contexts) == 1:
                        dev_grads.append(g)
                    else:
                        dev_grads.append(g[self.slices[i].start : self.slices[i].stop])
                ex.backward(dev_grads)

    def forward_backward(self, data_batch):
        """Fused per-device fwd+bwd: one XLA computation per device per step."""
        self.load_data_label(data_batch)
        for ex in self.execs:
            ex.forward_backward()

    def get_outputs(self, merge_multi_context=True):
        outputs = [[ex.outputs[i] for ex in self.execs] for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            return [outs[0] if len(outs) == 1 else nd.concatenate(outs, axis=0) for outs in outputs]
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        grads = [list(dev) for dev in self.input_grad_arrays]
        if merge_multi_context:
            return [g[0] if len(g) == 1 else nd.concatenate(g, axis=0) for g in grads]
        return grads

    def update_metric(self, eval_metric, labels):
        """(reference: executor_group.py:511)"""
        outputs = self.get_outputs(merge_multi_context=True)
        eval_metric.update(labels, outputs)

    # ---------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params, allow_extra_params=True)

    def get_params(self, arg_params, aux_params):
        """Copy device-0 values out (devices hold identical params)."""
        for i, name in enumerate(self.param_names):
            arg_params[name] = self.param_arrays[i][0].copy()
        for i, name in enumerate(self.aux_names):
            aux_params[name] = self.aux_arrays[i][0].copy()

    def install_monitor(self, mon):
        for ex in self.execs:
            mon.install(ex)


class _SharedProgramCarrier:
    """Minimal shared_exec stand-in carrying a _GraphProgram into bind()."""

    def __init__(self, prog, symbol):
        self._prog = prog
        self._symbol = symbol


class PipelineExecutorGroup:
    """GPipe-style pipeline-parallel execution of one Symbol.

    The auto-parallel planner's third axis (``parallel/autoplan.py``,
    docs/PARALLEL_PLANNER.md): when no dp × tp assignment fits the HBM
    budget, the graph is cut at single-tensor boundaries into stages, each
    stage binds its OWN executor (1/S of the parameters, gradients and
    optimizer state), and a batch runs as ``microbatches`` slices pushed
    through the stages — GPipe's schedule with recompute-based backward:

      forward phase   every microbatch m: stage 0..S-1 forward, stashing
                      the boundary activations per (m, stage) and the last
                      stage's outputs per m,
      backward phase  every microbatch m in REVERSE: stage S-1..0 reloads
                      m's inputs and runs the fused fwd+bwd program (the
                      cold-``backward`` path — a recompute, so no per-
                      microbatch activation stash survives in the
                      executors), handing each stage's boundary-input
                      gradient to the stage below; parameter grads
                      accumulate under ``grad_req='add'``.

    With per-example losses (SoftmaxOutput's default ``normalization=
    'null'``) the accumulated gradient over the microbatches equals the
    full-batch gradient exactly — tests assert parity at atol 1e-5.
    Caveats: BatchNorm running stats update once per microbatch forward
    (µ-fold faster momentum than one full-batch step), and stochastic ops
    (Dropout) draw fresh keys in the backward-phase recompute.
    """

    def __init__(self, symbol, context, data_shapes, label_shapes=None,
                 num_stages=2, microbatches=None, cut_entries=None,
                 type_dict=None, for_training=True, logger=None):
        from ..parallel import autoplan

        self.symbol = symbol
        self.context = context
        self.for_training = for_training
        self.data_shapes = [(d.name, tuple(d.shape)) if hasattr(d, "name")
                            else (d[0], tuple(d[1])) for d in data_shapes]
        self.label_shapes = [(l.name, tuple(l.shape)) if hasattr(l, "name")
                             else (l[0], tuple(l[1]))
                             for l in (label_shapes or [])]
        self.batch_size = self.data_shapes[0][1][0]
        mu = microbatches if microbatches is not None else \
            autoplan.autoplan_microbatches()
        if self.batch_size % mu:
            raise MXNetError(
                "batch size %d does not divide into %d microbatches"
                % (self.batch_size, mu))
        self.microbatches = mu
        self._mb = self.batch_size // mu

        full_shapes = dict(self.data_shapes + self.label_shapes)
        if cut_entries is None:
            cut_entries = autoplan.choose_cuts(
                symbol, full_shapes, types=type_dict, n_stages=num_stages)
        self.cut_entries = list(cut_entries)
        self.stage_symbols, self.boundary_names = autoplan.split_symbol(
            symbol, self.cut_entries)
        self.num_stages = len(self.stage_symbols)

        # ---- bind each stage at MICROBATCH shapes, chaining boundaries ----
        input_names = set(full_shapes)
        self.execs: List = []
        self._stage_inputs: List[List[str]] = []   # data/label vars per stage
        self._stage_params: List[List[str]] = []
        boundary_shape = None
        for k, ssym in enumerate(self.stage_symbols):
            args = ssym.list_arguments()
            stage_inputs = [n for n in args if n in input_names]
            bname = self.boundary_names[k - 1] if k > 0 else None
            params = [n for n in args
                      if n not in input_names and n != bname]
            shapes = {}
            for n in stage_inputs:
                sh = full_shapes[n]
                shapes[n] = (self._mb,) + tuple(sh[1:])
            grad_req = {n: "null" for n in stage_inputs}
            grad_req.update({n: "add" if for_training else "null"
                             for n in params})
            if bname is not None:
                shapes[bname] = boundary_shape
                grad_req[bname] = "write" if for_training else "null"
            ex = simple_bind(ssym, context, grad_req=grad_req,
                             type_dict=type_dict, **shapes)
            if k < self.num_stages - 1:
                _, out_shapes, _ = ssym.infer_shape(**shapes)
                boundary_shape = tuple(out_shapes[0])
            self.execs.append(ex)
            self._stage_inputs.append(stage_inputs)
            self._stage_params.append(params)

        self.param_names = [n for ps in self._stage_params for n in ps]
        self.aux_names = [n for s in self.stage_symbols
                          for n in s.list_auxiliary_states()]
        self.param_arrays = [self._owner(n).arg_dict[n]
                             for n in self.param_names]
        self.grad_arrays = [self._owner(n).grad_dict[n]
                            for n in self.param_names]
        self._outputs_mb: List[List[NDArray]] = []

    def _owner(self, param):
        for k, names in enumerate(self._stage_params):
            if param in names:
                return self.execs[k]
        raise MXNetError("parameter %r is bound by no stage" % param)

    # -------------------------------------------------------------- dataflow
    def _load_stage_inputs(self, ex, stage, data_map, m):
        lo, hi = m * self._mb, (m + 1) * self._mb
        for name in self._stage_inputs[stage]:
            src = data_map.get(name)
            if src is None:
                # a label-less predict batch: leave the bound array as-is
                # (the data side was validated in _batch_map)
                continue
            ex.arg_dict[name][:] = src[lo:hi]

    def _batch_map(self, data_batch):
        """Name -> host-numpy batch map, converted ONCE per batch — the
        schedule re-slices these for every (stage, microbatch, phase)."""
        def host(v):
            return v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)

        data_map = {n: host(v) for (n, _), v in
                    zip(self.data_shapes, data_batch.data or [])}
        if self.label_shapes and data_batch.label:
            data_map.update(
                {n: host(v) for (n, _), v in
                 zip(self.label_shapes, data_batch.label)})
        missing = [n for n, _ in self.data_shapes if n not in data_map]
        if missing:
            raise MXNetError("batch is missing input(s) %s" % missing)
        return data_map

    def forward(self, data_batch, is_train=None):
        """Chain every microbatch through the stages (forward phase only);
        boundary activations are stashed for a following ``backward``."""
        if is_train is None:
            is_train = self.for_training
        data_map = self._batch_map(data_batch)
        self._boundaries = [[None] * (self.num_stages - 1)
                            for _ in range(self.microbatches)]
        self._outputs_mb = []
        for m in range(self.microbatches):
            for k, ex in enumerate(self.execs):
                self._load_stage_inputs(ex, k, data_map, m)
                if k > 0:
                    ex.arg_dict[self.boundary_names[k - 1]][:] = \
                        self._boundaries[m][k - 1]
                ex.forward(is_train=is_train)
                # drop the vjp the train-mode forward stashed: backward
                # recomputes per microbatch anyway, and keeping it would pin
                # this stage's full residual set across the whole phase —
                # the memory this schedule exists to avoid
                ex._cached_vjp = None
                if k < self.num_stages - 1:
                    # boundary stash stays an NDArray (device-side; no
                    # host round-trip on the hop)
                    self._boundaries[m][k] = ex.outputs[0].copy()
            self._outputs_mb.append([o.copy() for o in self.execs[-1].outputs])
        self._data_map = data_map

    def backward(self):
        """Backward phase of the GPipe schedule (call after ``forward``):
        reverse microbatch order, fused fwd+bwd recompute per stage, grads
        accumulate across microbatches."""
        assert self.for_training, "bind with for_training=True"
        missing = [n for n, _ in self.label_shapes
                   if n not in self._data_map]
        if missing:
            raise MXNetError(
                "backward needs label input(s) %s but the batch carried "
                "none" % missing)
        for g in self.grad_arrays:
            if g is not None:
                g[:] = 0
        for m in reversed(range(self.microbatches)):
            out_grad = None
            for k in reversed(range(self.num_stages)):
                ex = self.execs[k]
                self._load_stage_inputs(ex, k, self._data_map, m)
                if k > 0:
                    ex.arg_dict[self.boundary_names[k - 1]][:] = \
                        self._boundaries[m][k - 1]
                # drop any vjp cached by the forward phase: it holds the
                # LAST microbatch's residuals, not microbatch m's — the
                # cold path below recomputes fwd+bwd fused on m's inputs
                ex._cached_vjp = None
                if k == self.num_stages - 1:
                    ex.backward()
                else:
                    ex.backward([out_grad])
                if k > 0:
                    out_grad = ex.grad_dict[self.boundary_names[k - 1]].copy()

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def get_outputs(self, merge_multi_context=True):
        """Last-stage outputs over the whole batch (microbatches
        re-concatenated along dim 0)."""
        n_out = len(self.execs[-1].outputs)
        return [nd.concatenate([mb[i] for mb in self._outputs_mb], axis=0)
                if self.microbatches > 1 else self._outputs_mb[0][i]
                for i in range(n_out)]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # ---------------------------------------------------------------- params
    def set_params(self, arg_params, aux_params=None):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params or {},
                                allow_extra_params=True)

    def get_params(self, arg_params, aux_params):
        for k, ex in enumerate(self.execs):
            for name in self._stage_params[k]:
                arg_params[name] = ex.arg_dict[name].copy()
            for name, arr in ex.aux_dict.items():
                aux_params[name] = arr.copy()
