"""Random number API.

Replaces the reference's python/mxnet/random.py + per-device mshadow::Random
resources (src/resource.cc:144 ResourceRandom). State is a single JAX PRNG key
split per draw — functional and reproducible across backends, unlike the
stateful per-device generators of the reference.
"""
from __future__ import annotations

import numpy as np

__all__ = ["seed", "uniform", "normal"]

_KEY = None


def _next_key():
    global _KEY
    import jax

    if _KEY is None:
        _KEY = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
    _KEY, sub = jax.random.split(_KEY)
    return sub


def _next_seed() -> int:
    """A fresh host-side integer seed derived from the global key (for numpy-
    based initializers like Orthogonal that need CPU linear algebra)."""
    import jax

    return int(jax.random.randint(_next_key(), (), 0, 2**31 - 1))


def seed(seed_state: int):
    """Seed the global generator (reference: mx.random.seed → MXRandomSeed)."""
    global _KEY
    import jax

    _KEY = jax.random.PRNGKey(int(seed_state))
    np.random.seed(int(seed_state) & 0x7FFFFFFF)


def refresh_backend():
    """Re-materialize the global key on the CURRENT backend (elastic
    re-form, docs/FAULT_TOLERANCE.md): the key's device buffer belongs to
    the torn-down backend, and if its last ``split`` dispatched into the
    failed collective era its definition event is poisoned — the first
    post-re-form draw would then die with the OLD generation's transport
    error. A key whose buffer is unreadable is dropped; the next draw
    re-seeds (weights/optimizer state come from the checkpoint, so RNG
    continuity across a crash is best-effort by design)."""
    global _KEY
    if _KEY is None:
        return
    import jax.numpy as jnp

    try:
        host = np.asarray(_KEY)
    except Exception:
        _KEY = None
        return
    _KEY = jnp.asarray(host)


def uniform(low=0.0, high=1.0, shape=(1,), ctx=None, dtype=np.float32, out=None):
    from .ndarray import imperative_invoke
    from .context import current_context

    attrs = {"low": low, "high": high, "shape": shape, "dtype": dtype}
    return imperative_invoke("random_uniform", [], attrs, ctx=ctx or current_context(), out=out)[0]


def normal(loc=0.0, scale=1.0, shape=(1,), ctx=None, dtype=np.float32, out=None):
    from .ndarray import imperative_invoke
    from .context import current_context

    attrs = {"loc": loc, "scale": scale, "shape": shape, "dtype": dtype}
    return imperative_invoke("random_normal", [], attrs, ctx=ctx or current_context(), out=out)[0]
