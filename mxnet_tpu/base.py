"""Base utilities: dtypes, errors, naming.

TPU-native replacement for the ctypes plumbing in the reference's
``python/mxnet/base.py``. There is no C ABI boundary here — the Python layer
talks straight to JAX — so this module only keeps the pieces of ``base.py``
that are API surface: ``MXNetError``, dtype name<->numpy mapping
(reference: python/mxnet/ndarray.py:36-52 ``_DTYPE_NP_TO_MX``), and name
mangling helpers.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "EvictedError", "string_types", "numeric_types",
           "anomaly_guard_mode"]


class MXNetError(Exception):
    """Error raised by the framework (reference: python/mxnet/base.py MXNetError)."""


class EvictedError(MXNetError):
    """This worker was evicted from an elastic job (docs/FAULT_TOLERANCE.md):
    the surviving membership re-formed without it — either because it is
    draining after SIGTERM (expected; exit 0) or because its heartbeat went
    stale from the coordinator's point of view (clock skew / stalled host).
    Rejoining a generation that has written this worker off would corrupt
    the collective, so the only safe move is to stop training and exit."""


string_types = (str,)
numeric_types = (float, int, np.generic)

_warned_anomaly_modes = set()


def anomaly_guard_mode():
    """MXNET_ANOMALY_GUARD (docs/RESILIENCE.md): post-backward NaN/Inf
    gradient guard in the training loop. Returns None (off, the default),
    ``"skip"`` (drop the anomalous step: no weight/optimizer/aux update,
    count it, warn with the first offending key) or ``"raise"`` (throw a
    structured MXNetError naming the key — state is left UN-updated either
    way, so a caught raise can lower the lr and continue). Unrecognized
    values warn once and stay off."""
    import os

    raw = os.environ.get("MXNET_ANOMALY_GUARD", "0").strip().lower()
    if raw in ("", "0", "off", "false", "none", "no"):
        return None
    if raw in ("skip", "raise"):
        return raw
    if raw not in _warned_anomaly_modes:
        _warned_anomaly_modes.add(raw)
        import logging

        logging.getLogger("mxnet_tpu").warning(
            "MXNET_ANOMALY_GUARD=%r is not one of 0|skip|raise; the "
            "anomaly guard stays OFF", raw)
    return None

# dtype code table, numerically compatible with the reference's
# _DTYPE_NP_TO_MX / _DTYPE_MX_TO_NP (python/mxnet/ndarray.py:36-52) so that
# serialized .params files round-trip.
_DTYPE_NP_TO_MX = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    # TPU-native extensions (codes unused by the reference)
    np.dtype(np.int64): 6,
    np.dtype(np.bool_): 7,
}
_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}

try:  # bfloat16 is the TPU-native compute dtype; register if available
    import ml_dtypes  # noqa: F401

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    _DTYPE_NP_TO_MX[_BFLOAT16] = 5
    _DTYPE_MX_TO_NP[5] = _BFLOAT16
except ImportError:  # pragma: no cover
    _BFLOAT16 = None


def np_dtype(dtype) -> np.dtype:
    """Normalize a user-provided dtype (str, np type, jnp dtype) to np.dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16" and _BFLOAT16 is not None:
        return _BFLOAT16
    return np.dtype(dtype)


def dtype_code(dtype) -> int:
    d = np_dtype(dtype)
    if d not in _DTYPE_NP_TO_MX:
        raise MXNetError("unsupported dtype %s" % d)
    return _DTYPE_NP_TO_MX[d]


def dtype_from_code(code: int) -> np.dtype:
    if code not in _DTYPE_MX_TO_NP:
        raise MXNetError("unsupported dtype code %d" % code)
    return _DTYPE_MX_TO_NP[code]
